"""Grammar-constrained structured output tests (docs/structured-output.md).

Core invariants, in roughly the order they are built:

- compiler: the JSON-schema/EBNF front-ends accept exactly their
  language, refuse unsupported constructs by path, and the token DFA's
  cursor walks valid serializations to a terminal state;
- engine: under the gmask operand a temp>0 slot can NEVER emit a
  grammar-illegal token (randomized-schema property test), an all-allow
  mask is token-identical to the unmasked engine (greedy parity), and
  constrained slots ride speculative verify unchanged (on/off parity,
  dense AND paged);
- lifecycle: preempt/swap-resume carries the DFA cursor loss-free (the
  PR-16 loss-free-resume discipline), and a steady mixed loop of
  constrained + unconstrained + LoRA traffic performs ZERO XLA compiles
  (masked program variants replace the plain set, never multiply it);
- surface: response_format end-to-end over HTTP with typed 400s
  (unsupported constructs, unknown top-level body fields), the gateway
  forwarding the field verbatim, and controller spec validation.
"""

import dataclasses
import json
import os
import random

import jax
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import init_params
from runbooks_tpu.serve.engine import InferenceEngine, Request
from runbooks_tpu.serve.grammar import (
    GrammarCache,
    GrammarError,
    TokenVocab,
    ebnf_to_ast,
    response_format_ast,
    schema_to_ast,
)
from runbooks_tpu.serve.paging import PagedInferenceEngine
from runbooks_tpu.serve.speculative import legal_draft_prefix
from runbooks_tpu.train.data import ByteTokenizer


def tiny_cfg(**over):
    # vocab_size matches the ByteTokenizer (258 = 256 bytes + bos + eos)
    # so the grammar mask width covers the tokenizer's eos id.
    base = dict(vocab_size=258, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                max_seq_len=64, dtype="float32")
    base.update(over)
    return dataclasses.replace(get_config("llama2-7b"), **base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.key(0))


@pytest.fixture(scope="module")
def bank(model):
    """Lazily built, module-shared engines. Engine construction is
    cheap but the first dispatch compiles the program set — sharing
    instances across tests keeps the suite inside the tier-1 wall
    budget. Only stateless-use tests draw from the bank; tests that
    assert engine counters or sentinel state build their own."""
    cfg, params = model
    engines = {}

    def get(kind, grammar=False, spec=False):
        key = (kind, grammar, spec)
        if key not in engines:
            kw = dict(max_slots=2)
            if kind == "paged":
                kw["page_size"] = 16
            if grammar:
                kw.update(grammar="on", tokenizer=TOK)
            if spec:
                kw.update(speculative="ngram", draft_tokens=4)
            cls = PagedInferenceEngine if kind == "paged" \
                else InferenceEngine
            engines[key] = cls(cfg, params, **kw)
        return engines[key]

    return get


TOK = ByteTokenizer()
VOCAB = TokenVocab.from_tokenizer(TOK)


def _cache(capacity=8):
    return GrammarCache(VOCAB, 258, capacity=capacity)


def _prompt(text=b"emit json: "):
    return [int(b) for b in text]


def _text(req):
    return bytes(t for t in req.output_tokens if t < 256).decode()


SCHEMA_RF = {"type": "json_schema", "json_schema": {"schema": {
    "type": "object",
    "properties": {"ok": {"type": "boolean"},
                   "mode": {"enum": ["a", "b"]}},
    "required": ["ok", "mode"],
    "additionalProperties": False,
}}}


# ---------------------------------------------------------------------------
# Compiler: vocab fingerprint, schema/EBNF front-ends, DFA cursor
# ---------------------------------------------------------------------------

def test_token_vocab_fingerprint_stable():
    # Content hash, not object identity: two tokenizer instances with
    # the same vocab must key the same cache entries.
    a = TokenVocab.from_tokenizer(ByteTokenizer())
    b = TokenVocab.from_tokenizer(ByteTokenizer())
    assert a.fingerprint == b.fingerprint == VOCAB.fingerprint
    assert len(a.fingerprint) == 64          # sha256 hex


def test_cursor_walks_valid_json_to_terminal():
    dfa = _cache().get(SCHEMA_RF)
    cur = dfa.cursor()
    for b in b'{"ok":true,"mode":"a"}':
        assert cur.legal(b), chr(b)
        assert cur.advance(b)
    assert cur.accepting and cur.at_terminal
    # terminal = nothing but EOS: the mask row allows exactly eos.
    row = cur.mask_row()
    assert row[VOCAB.eos_id]
    assert int(row.sum()) == 1
    # an illegal byte neither validates nor mutates
    cur2 = dfa.cursor()
    assert not cur2.legal(ord("x"))
    state_before = cur2.state
    assert not cur2.advance(ord("x"))
    assert cur2.state == state_before


def test_schema_unsupported_constructs_raise_with_path():
    cases = [
        ({"type": "object", "properties": {"a": {"$ref": "#/x"}},
          "required": ["a"], "additionalProperties": False},
         "$.a"),
        ({"oneOf": [{"type": "null"}]}, "oneOf"),
        ({"type": "string", "pattern": "a+"}, "pattern"),
        ({"type": "object", "properties": {"a": {"type": "null"}},
          "additionalProperties": True}, "additionalProperties"),
        ({"type": "object", "properties": {"a": {"type": "null"}},
          "required": [], "additionalProperties": False}, "required"),
        ({"type": "array", "items": {"type": "null"}, "minItems": 2},
         "minItems"),
        ({"type": ["string", "null"]}, "union"),
        ({"type": "frobnicate"}, "frobnicate"),
    ]
    for schema, needle in cases:
        with pytest.raises(GrammarError, match=None) as ei:
            schema_to_ast(schema)
        assert needle in str(ei.value), (schema, str(ei.value))
    with pytest.raises(GrammarError, match="json_object"):
        response_format_ast({"type": "json_object"})
    with pytest.raises(GrammarError, match="json_schema or ebnf"):
        response_format_ast({"type": "jsonschema"})


def test_ebnf_compiles_and_recursion_rejected():
    rf = {"type": "ebnf", "grammar": (
        '# toy signed integer\n'
        'root ::= sign? digit digit*\n'
        'sign ::= "-"\n'
        'digit ::= [0-9]\n')}
    dfa = _cache().get(rf)
    cur = dfa.cursor()
    for b in b"-42":
        assert cur.advance(b)
    assert cur.accepting
    assert not dfa.cursor().legal(ord("a"))
    with pytest.raises(GrammarError, match="recursive"):
        ebnf_to_ast('root ::= "(" root ")"')
    with pytest.raises(GrammarError, match="undefined"):
        ebnf_to_ast("root ::= missing")


def test_cache_lru_eviction_and_stats():
    cache = _cache(capacity=2)
    rfs = [{"type": "ebnf", "grammar": f'root ::= "{c}"'}
           for c in "abc"]
    cache.get(rfs[0])
    cache.get(rfs[0])                        # hit
    cache.get(rfs[1])
    cache.get(rfs[2])                        # evicts rfs[0]
    cache.get(rfs[0])                        # recompiles
    st = cache.stats()
    assert st["size"] == 2 and st["capacity"] == 2
    assert st["hits"] == 1 and st["misses"] == 4
    assert st["compile_seconds_total"] > 0
    assert st["tokenizer_fingerprint"] == VOCAB.fingerprint
    with pytest.raises(ValueError, match="grammar_cache_size"):
        GrammarCache(VOCAB, 258, capacity=0)


def test_legal_draft_prefix_truncates_illegal_and_terminal():
    dfa = _cache().get({"type": "ebnf", "grammar": 'root ::= "ab"'})
    cur = dfa.cursor()
    # illegal mid-draft: cut before the first token the DFA refuses
    assert legal_draft_prefix(cur, [ord("a"), ord("x")]) == [ord("a")]
    # a draft crossing the terminal accept state is cut there — the
    # slot finishes with grammar_complete and must not propose past it
    assert legal_draft_prefix(
        cur, [ord("a"), ord("b"), ord("a")]) == [ord("a"), ord("b")]
    # non-mutating: the cursor itself never advanced
    assert cur.state == dfa.cursor().state
    # unconstrained cursors pass drafts through untouched
    assert legal_draft_prefix(None, [1, 2, 3]) == [1, 2, 3]


# ---------------------------------------------------------------------------
# Engine: property test (temp>0 never illegal), parity, spec decode
# ---------------------------------------------------------------------------

def _random_schema(rng, depth=0):
    """Random schema from the supported subset. Leaves are finite
    (boolean/null/enum/const/integer) so the language is decidable per
    token; containers recurse with shrinking probability."""
    leaves = [
        {"type": "boolean"},
        {"type": "null"},
        {"type": "integer"},
        {"enum": [rng.choice(["x", "y", 1, True])]},
        {"const": rng.choice([0, "k", False, None])},
    ]
    if depth >= 2 or rng.random() < 0.4:
        return rng.choice(leaves)
    if rng.random() < 0.5:
        props = {f"p{i}": _random_schema(rng, depth + 1)
                 for i in range(rng.randint(1, 3))}
        return {"type": "object", "properties": props,
                "required": sorted(props), "additionalProperties": False}
    return {"type": "array", "items": _random_schema(rng, depth + 1),
            "minItems": rng.randint(0, 1)}


def test_random_schemas_temp_sampling_never_illegal(bank):
    """Property test: under the gmask operand, a temp>0 constrained slot
    never emits a token its DFA state forbids — verified by replaying
    every output through a fresh cursor. Completed slots parse as JSON
    the schema accepts structurally."""
    engine = bank("dense", grammar=True)
    cache = _cache(capacity=32)
    rng = random.Random(0)
    reqs = []
    for i in range(8):
        rf = {"type": "json_schema",
              "json_schema": {"schema": _random_schema(rng)}}
        reqs.append(Request(
            prompt_tokens=_prompt(), max_tokens=48,
            temperature=1.5, eos_id=TOK.eos_id, response_format=rf))
    engine.generate(reqs)
    for r in reqs:
        assert r.finish_reason != "error"
        cur = cache.cursor(r.response_format)
        for t in r.output_tokens:
            if t == TOK.eos_id:
                assert cur.accepting     # EOS only at accept states
                break
            assert cur.advance(t), (r.response_format, _text(r), t)
        if r.finish_reason == "grammar_complete":
            json.loads(_text(r))         # 100% parse on completion


def test_full_parse_rate_bounded_schemas(bank):
    """Finite-language schemas (no stars) must complete and parse 100%
    of the time — the bench gate's assertion, test-sized."""
    engine = bank("dense", grammar=True)
    rf = {"type": "json_schema", "json_schema": {"schema": {
        "type": "object",
        "properties": {"a": {"type": "boolean"},
                       "b": {"enum": ["u", "v", "w"]},
                       "c": {"type": "null"}},
        "required": ["a", "b", "c"], "additionalProperties": False}}}
    reqs = [Request(prompt_tokens=_prompt(), max_tokens=48,
                    temperature=t, eos_id=TOK.eos_id, response_format=rf)
            for t in (0.0, 0.7, 1.0, 1.5)]
    engine.generate(reqs)
    for r in reqs:
        assert r.finish_reason == "grammar_complete"
        out = json.loads(_text(r))
        assert set(out) == {"a", "b", "c"}
        assert isinstance(out["a"], bool)
        assert out["b"] in ("u", "v", "w") and out["c"] is None


@pytest.mark.parametrize("engine_cls", ["dense", "paged"])
def test_greedy_all_allow_mask_parity(bank, engine_cls):
    """A grammar-on engine serving UNCONSTRAINED requests dispatches
    all-allow mask rows — `where(True, logits, -inf)` is the identity,
    so greedy output is token-identical to the grammar-off engine."""
    plain = bank(engine_cls)
    masked = bank(engine_cls, grammar=True)
    prompts = [_prompt(b"hello"), _prompt(b"abc def")]
    for prompt in prompts:
        a = Request(prompt_tokens=list(prompt), max_tokens=8,
                    temperature=0.0, eos_id=TOK.eos_id)
        b = Request(prompt_tokens=list(prompt), max_tokens=8,
                    temperature=0.0, eos_id=TOK.eos_id)
        plain.generate([a])
        masked.generate([b])
        assert a.output_tokens == b.output_tokens
        assert a.finish_reason == b.finish_reason


@pytest.mark.parametrize("engine_cls", ["dense", "paged"])
def test_spec_decode_parity_constrained(bank, engine_cls):
    """Constrained greedy output is token-identical with speculation on
    or off: drafts are pre-truncated to legal prefixes so the verify
    math never sees a zero-mass token, and per-position masks replay
    the same DFA states the sequential path visits."""
    base = bank(engine_cls, grammar=True)
    spec = bank(engine_cls, grammar=True, spec=True)
    for rf in (SCHEMA_RF,
               {"type": "ebnf",
                "grammar": 'root ::= "[" [0-9] ("," [0-9])* "]"'}):
        a = Request(prompt_tokens=_prompt(), max_tokens=24,
                    temperature=0.0, eos_id=TOK.eos_id,
                    response_format=rf)
        b = Request(prompt_tokens=_prompt(), max_tokens=24,
                    temperature=0.0, eos_id=TOK.eos_id,
                    response_format=rf)
        base.generate([a])
        spec.generate([b])
        assert a.output_tokens == b.output_tokens
        assert a.finish_reason == b.finish_reason


# ---------------------------------------------------------------------------
# Lifecycle: preempt/swap-resume carries the cursor, zero compiles
# ---------------------------------------------------------------------------

def test_preemption_resumes_grammar_cursor_loss_free(model):
    """Swap preemption requeues the Request object — its DFA cursor
    rides along, so the resumed constrained decode continues from the
    exact grammar state and the final output is token-identical to an
    undisturbed run (the loss-free-resume discipline, grammar
    edition)."""
    cfg, params = model
    # Fixed-literal properties => a long deterministic constrained
    # rollout (42 tokens) that stays mid-flight across several decode
    # steps yet fits max_seq_len with the prompt.
    rf = {"type": "json_schema", "json_schema": {"schema": {
        "type": "object",
        "properties": {f"k{i}": {"const": v} for i, v in
                       enumerate([True, None, "aa", False])},
        "required": [f"k{i}" for i in range(4)],
        "additionalProperties": False}}}

    def constrained(priority):
        return Request(prompt_tokens=_prompt(), max_tokens=50,
                       temperature=0.0, eos_id=TOK.eos_id,
                       response_format=rf, priority=priority)

    oracle = constrained("batch")
    undisturbed = PagedInferenceEngine(
        cfg, params, max_slots=1, page_size=16, num_pages=5,
        kv_host_pages=8, preemption="swap", decode_chunk=2,
        grammar="on", tokenizer=TOK)
    undisturbed.generate([oracle])
    assert oracle.finish_reason == "grammar_complete"
    json.loads(_text(oracle))

    engine = PagedInferenceEngine(
        cfg, params, max_slots=1, page_size=16, num_pages=5,
        kv_host_pages=8, preemption="swap", decode_chunk=2,
        grammar="on", tokenizer=TOK)
    batch = constrained("batch")
    engine.submit(batch)
    for _ in range(3):
        engine.step()
    assert engine.active.any() and not batch.finished
    inter = Request(prompt_tokens=_prompt(b"quick"), max_tokens=4,
                    temperature=0.0, eos_id=TOK.eos_id,
                    priority="interactive")
    engine.submit(inter)
    engine.step()
    assert engine.preemptions == 1 and not batch.finished
    while engine.has_work():
        engine.step()
    assert engine.preempted_resumed == 1
    assert batch.output_tokens == oracle.output_tokens
    assert batch.finish_reason == "grammar_complete"


def test_zero_unexpected_compiles_mixed_grammar_lora_loop(
        model, tmp_path):
    """Warmed grammar-on pooled engine: a steady loop mixing
    constrained, unconstrained, and LoRA-adapter requests performs ZERO
    XLA compiles — the gmask operand rides every dispatch (all-allow
    rows for unconstrained lanes) so masked program variants replace
    the plain set instead of multiplying the census.

    Dense engine only: a full paged warmup costs ~30 s of compiles on
    CPU and the paged grammar dispatch is already covered by the parity
    and preemption tests here plus the bench gate (bench_sweep §4a8);
    the mixed-traffic zero-compile property itself is engine-agnostic."""
    engine_cls = "dense"
    from runbooks_tpu.obs import device as obs_device
    from runbooks_tpu.serve.lora_pool import save_adapter
    from runbooks_tpu.train.lora import LoraConfig, init_lora

    cfg, params = model
    c = dataclasses.replace(cfg, adapter_pool=2, lora_rank=8)
    lora = init_lora(params, LoraConfig(rank=4, alpha=8.0),
                     jax.random.key(11))
    lora = jax.tree.map(
        lambda x: x + 0.03 * jax.random.normal(
            jax.random.key(21), x.shape, x.dtype), lora)
    path = os.path.join(str(tmp_path), "tenant0")
    save_adapter(path, lora, rank=4, alpha=8.0)

    if engine_cls == "paged":
        eng = PagedInferenceEngine(c, params, max_slots=2, page_size=16,
                                   grammar="on", tokenizer=TOK)
    else:
        eng = InferenceEngine(c, params, max_slots=2, grammar="on",
                              tokenizer=TOK)
    sentinel = obs_device.SENTINEL
    if not sentinel.install():
        pytest.skip("jax.monitoring unavailable; sentinel cannot verify")
    eng.warmup()
    census = eng.warmup_census
    assert census["grammar"] == "on"
    assert census["grammar_cache_size"] == 64
    before_total = sentinel.total
    before_unexpected = sentinel.unexpected
    try:
        for i in range(6):
            r = Request(
                prompt_tokens=_prompt(), max_tokens=6, temperature=0.0,
                eos_id=TOK.eos_id,
                response_format=SCHEMA_RF if i % 3 == 0 else None,
                adapter=path if i % 3 == 1 else None)
            eng.generate([r])
            assert r.finished and r.finish_reason != "error"
        stats = eng.grammar_stats()
        assert stats["requests_total"] == 2      # the loop really mixed
        assert stats["hits"] >= 1                # ...and the cache hit
        assert sentinel.total == before_total, "compiled under traffic"
        assert sentinel.unexpected == before_unexpected
    finally:
        eng.release_steady()


# ---------------------------------------------------------------------------
# Engine/controller validation
# ---------------------------------------------------------------------------

def test_engine_grammar_validation(model):
    cfg, params = model
    with pytest.raises(ValueError, match="grammar"):
        InferenceEngine(cfg, params, max_slots=1, grammar="maybe")
    with pytest.raises(ValueError, match="tokenizer"):
        InferenceEngine(cfg, params, max_slots=1, grammar="on")
    off = InferenceEngine(cfg, params, max_slots=1)
    with pytest.raises(ValueError, match="grammar: on"):
        off.submit(Request(prompt_tokens=[1, 2],
                           response_format=SCHEMA_RF))
    on = InferenceEngine(cfg, params, max_slots=1, grammar="on",
                         tokenizer=TOK)
    with pytest.raises(ValueError, match="unsupported schema construct"):
        on.submit(Request(prompt_tokens=[1, 2], response_format={
            "type": "json_schema",
            "json_schema": {"schema": {"oneOf": []}}}))
    assert on.tokenizer_fingerprint == VOCAB.fingerprint


def test_validate_params_grammar():
    from runbooks_tpu.controller.common import validate_params

    assert validate_params({"grammar": "on"}) is None
    assert validate_params({"grammar": "on",
                            "grammar_cache_size": 4}) is None
    assert "grammar" in validate_params({"grammar": "maybe"})
    assert ">= 1" in validate_params({"grammar": "on",
                                      "grammar_cache_size": 0})
    # cache knob without the mode is a spec typo, not a silent no-op
    err = validate_params({"grammar_cache_size": 8})
    assert err is not None and "grammar: on" in err
    err = validate_params({"grammar": "off", "grammarCacheSize": 8})
    assert err is not None and "grammar: on" in err


# ---------------------------------------------------------------------------
# HTTP surface + gateway forwarding
# ---------------------------------------------------------------------------

def test_http_response_format_end_to_end(model):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg, params = model
    app = create_server(cfg, params, tokenizer=ByteTokenizer(),
                        max_slots=2, grammar="on")

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "emit json: ", "max_tokens": 32,
                "temperature": 0.0, "response_format": SCHEMA_RF})
            assert r.status == 200
            body = await r.json()
            choice = body["choices"][0]
            assert choice["finish_reason"] == "grammar_complete"
            out = json.loads(choice["text"])
            assert set(out) == {"ok", "mode"}

            # unsupported construct -> typed 400 naming the path
            r = await client.post("/v1/completions", json={
                "prompt": "x", "response_format": {
                    "type": "json_schema", "json_schema": {"schema": {
                        "type": "string", "pattern": "a+"}}}})
            assert r.status == 400
            assert "pattern" in (await r.json())["error"]["message"]

            # non-object response_format -> 400 before admission
            r = await client.post("/v1/completions", json={
                "prompt": "x", "response_format": "json"})
            assert r.status == 400

            # a TYPO'D field must 400 listing the unknown names, never
            # silently serve unconstrained output
            r = await client.post("/v1/completions", json={
                "prompt": "x", "respose_format": SCHEMA_RF})
            assert r.status == 400
            err = (await r.json())["error"]
            assert err["type"] == "unknown_field"
            assert err["fields"] == ["respose_format"]
            assert "respose_format" in err["message"]

            # observability: grammar families + tokenizer fingerprint
            r = await client.get("/metrics")
            text = await r.text()
            assert "serve_grammar_requests_total" in text
            assert "serve_grammar_cache_misses_total" in text
            r = await client.get("/debug/programs")
            dbg = await r.json()
            assert dbg["tokenizer_fingerprint"] == VOCAB.fingerprint
            assert dbg["grammar"]["mode"] == "on"
            assert dbg["grammar"]["requests_total"] >= 1

    asyncio.run(drive())


def test_http_response_format_rejected_when_grammar_off(model):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg, params = model
    app = create_server(cfg, params, tokenizer=ByteTokenizer(),
                        max_slots=1)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "x", "response_format": SCHEMA_RF})
            assert r.status == 400
            msg = (await r.json())["error"]["message"]
            assert "grammar: on" in msg
            r = await client.get("/debug/programs")
            dbg = await r.json()
            assert dbg["grammar"] == {"mode": "off"}
            # fingerprint exposed even with grammar off: fleet audits
            # compare replica vocabs BEFORE enabling constrained routing
            assert dbg["tokenizer_fingerprint"] == VOCAB.fingerprint

    asyncio.run(drive())


def test_gateway_forwards_response_format():
    import asyncio

    from aiohttp import web
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.gateway import create_gateway

    async def drive():
        replica = web.Application()
        replica["hits"] = []

        async def completions(request):
            body = await request.json()
            replica["hits"].append(body)
            return web.json_response({"choices": [{
                "text": '{"ok":true,"mode":"a"}',
                "finish_reason": "grammar_complete"}]})

        replica.router.add_post("/v1/completions", completions)
        srv = TestServer(replica)
        await srv.start_server()
        gw = create_gateway({"a": f"http://127.0.0.1:{srv.port}"},
                            scrape_interval_s=0)
        async with TestClient(TestServer(gw)) as client:
            resp = await client.post("/v1/completions", json={
                "prompt": "emit json: ", "max_tokens": 32,
                "response_format": SCHEMA_RF})
            assert resp.status == 200
            data = await resp.json()
            # finish_reason passes through the proxy verbatim
            assert data["choices"][0]["finish_reason"] \
                == "grammar_complete"
        # the replica saw the field byte-for-byte — the gateway forwards
        # the whole body without learning the grammar schema
        assert replica["hits"][0]["response_format"] == SCHEMA_RF
        await srv.close()

    asyncio.run(drive())
