"""SCI tests: hermetic gRPC loopback + HTTP PUT -> MD5 flow (the analog of
the reference's fully-hermetic kind SCI test — internal/sci/kind/
server_test.go)."""

import asyncio
import hashlib

import pytest

from runbooks_tpu.sci.base import FakeSCI, LocalSCI
from runbooks_tpu.sci.grpc_service import GrpcSCI, serve


@pytest.fixture()
def local_sci(tmp_path):
    return LocalSCI(root=str(tmp_path / "bucket"),
                    endpoint="http://localhost:30080")


def test_grpc_roundtrip(local_sci):
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    server = serve(local_sci, port=port)
    try:
        client = GrpcSCI(f"localhost:{port}", timeout=10)
        url = client.create_signed_url("bkt", "uploads/latest.tar.gz",
                                       md5_checksum="aa")
        assert url.startswith("http://localhost:30080/bkt/uploads/")
        # object not there yet
        assert client.get_object_md5("bkt", "uploads/latest.tar.gz") is None
        md5 = local_sci.put_object("bkt", "uploads/latest.tar.gz", b"hello")
        assert client.get_object_md5("bkt", "uploads/latest.tar.gz") == md5
        client.bind_identity("p@proj.iam", "modeller", "default")  # no-op ok
    finally:
        server.stop(grace=0)


def test_http_put_endpoint(local_sci):
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.sci.http_endpoint import create_app

    app = create_app(local_sci)
    payload = b"tarball-bytes"
    md5 = hashlib.md5(payload).hexdigest()

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.put("/bkt/uploads/latest.tar.gz", data=payload,
                                 headers={"Content-MD5": md5})
            assert r.status == 200
            body = await r.json()
            assert body["md5"] == md5

            # bad md5 header rejected
            r = await client.put("/bkt/uploads/other.tar.gz", data=payload,
                                 headers={"Content-MD5": "0" * 32})
            assert r.status == 400

            # expired signed URL rejected
            r = await client.put("/bkt/uploads/latest.tar.gz?expiry=1",
                                 data=payload)
            assert r.status == 403

    asyncio.run(drive())
    assert local_sci.get_object_md5("bkt", "uploads/latest.tar.gz") == md5
