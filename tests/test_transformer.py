"""Model forward-pass correctness tests.

Mirrors the reference's test philosophy (SURVEY.md §4: hermetic, no cloud/
hardware deps) — everything runs on the 8-device virtual CPU platform from
conftest.py.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import KVCache, forward, init_params


def tiny(family: str):
    base = get_config(family)
    return dataclasses.replace(
        base, vocab_size=256, hidden_size=64,
        intermediate_size=128 if not base.gated_mlp else 96,
        num_layers=2, num_heads=4,
        num_kv_heads=2 if base.num_kv_heads < base.num_heads else 4,
        head_dim=16, max_seq_len=64,
        dtype="float32",  # exact-math tests; bf16 noise tested separately
    )


FAMILIES = ["llama2-7b", "falcon-7b", "opt-125m"]


@pytest.mark.parametrize("family", FAMILIES)
def test_forward_shapes_finite(family):
    cfg = tiny(family)
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    logits, cache = forward(cfg, params, tokens)
    assert cache is None
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    assert bool(jnp.isfinite(logits).all())


@pytest.mark.parametrize("family", FAMILIES)
def test_causality(family):
    cfg = tiny(family)
    params = init_params(cfg, jax.random.key(0))
    t1 = jax.random.randint(jax.random.key(1), (1, 12), 0, cfg.vocab_size)
    t2 = t1.at[0, -1].set((t1[0, -1] + 1) % cfg.vocab_size)
    l1, _ = forward(cfg, params, t1)
    l2, _ = forward(cfg, params, t2)
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], rtol=2e-4, atol=2e-4)
    assert not np.allclose(l1[0, -1], l2[0, -1])


@pytest.mark.parametrize("family", FAMILIES)
def test_kv_cache_matches_full_forward(family):
    cfg = tiny(family)
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 10), 0, cfg.vocab_size)

    full_logits, _ = forward(cfg, params, tokens)

    # Chunked prefill (6 tokens) + token-by-token decode.
    cache = KVCache.create(cfg, batch=2, max_len=16)
    logits_pre, cache = forward(cfg, params, tokens[:, :6], cache=cache)
    got = [logits_pre]
    for i in range(6, 10):
        step_logits, cache = forward(cfg, params, tokens[:, i:i + 1], cache=cache)
        got.append(step_logits)
    cached_logits = jnp.concatenate(got, axis=1)
    np.testing.assert_allclose(full_logits, cached_logits, rtol=2e-5, atol=2e-5)
    assert int(cache.index) == 10


def test_packed_segments_are_isolated():
    cfg = tiny("llama2-7b")
    params = init_params(cfg, jax.random.key(0))
    a = jax.random.randint(jax.random.key(1), (1, 5), 0, cfg.vocab_size)
    b = jax.random.randint(jax.random.key(2), (1, 7), 0, cfg.vocab_size)

    packed = jnp.concatenate([a, b], axis=1)
    segs = jnp.asarray([[1] * 5 + [2] * 7], jnp.int32)
    positions = jnp.asarray([list(range(5)) + list(range(7))], jnp.int32)
    lp, _ = forward(cfg, params, packed, positions=positions, segment_ids=segs)

    la, _ = forward(cfg, params, a)
    lb, _ = forward(cfg, params, b)
    np.testing.assert_allclose(lp[0, :5], la[0], rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(lp[0, 5:], lb[0], rtol=2e-5, atol=2e-5)


def test_padding_segment_zero_is_masked():
    cfg = tiny("llama2-7b")
    params = init_params(cfg, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab_size)
    segs = jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.int32)
    l1, _ = forward(cfg, params, toks, segment_ids=segs)
    # Changing padding tokens must not change real-token logits.
    toks2 = toks.at[0, 5].set((toks[0, 5] + 3) % cfg.vocab_size)
    l2, _ = forward(cfg, params, toks2, segment_ids=segs)
    np.testing.assert_allclose(l1[0, :4], l2[0, :4], rtol=1e-5, atol=1e-5)


def test_remat_matches_no_remat():
    cfg = tiny("llama2-7b")
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab_size)
    l1, _ = forward(cfg, params, tokens)
    l2, _ = forward(cfg, params, tokens, remat=True)
    np.testing.assert_allclose(l1, l2, rtol=1e-6, atol=1e-6)


def test_save_attn_out_policy_matches_full_remat():
    # The selective policy (save only the named attn_out tensor) must not
    # change numerics — forward or gradients — vs full remat and no remat.
    cfg = tiny("llama2-7b")
    sel = dataclasses.replace(cfg, remat_policy="save_attn_out")
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab_size)

    def loss(c, p):
        logits, _ = forward(c, p, tokens, remat=True)
        return jnp.mean(jax.nn.log_softmax(logits)[..., 0])

    l1, g1 = jax.value_and_grad(lambda p: loss(cfg, p))(params)
    l2, g2 = jax.value_and_grad(lambda p: loss(sel, p))(params)
    np.testing.assert_allclose(l1, l2, rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_unknown_remat_policy_raises():
    cfg = dataclasses.replace(tiny("llama2-7b"), remat_policy="bogus")
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg.vocab_size)
    with pytest.raises(ValueError, match="unknown remat_policy"):
        forward(cfg, params, tokens, remat=True)


def test_bf16_forward_close_to_fp32():
    cfg32 = tiny("llama2-7b")
    cfg16 = dataclasses.replace(cfg32, dtype="bfloat16")
    params = init_params(cfg32, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (1, 8), 0, cfg32.vocab_size)
    l32, _ = forward(cfg32, params, tokens)
    l16, _ = forward(cfg16, params, tokens)
    # bf16 activations should track fp32 within a few percent on a tiny model.
    assert float(jnp.max(jnp.abs(l32 - l16))) < 0.15


def test_param_count_matches_config():
    from runbooks_tpu.models.config import ModelConfig

    for family in FAMILIES:
        cfg = tiny(family)
        params = init_params(cfg, jax.random.key(0))
        n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
        assert n == cfg.num_params, f"{family}: {n} != {cfg.num_params}"
