"""Wire-level K8sClient tests against the HTTP apiserver fake.

Round-1 gap (VERDICT item 5): the real REST client had only ever run
against the in-memory FakeCluster object interface, so its HTTP layer (URL
construction, SSA patch content type + field manager, status subresource,
watch stream parsing, 404/409 handling) was untested — and the fake had
already masked one SSA bug. These tests put real bytes on a real socket.
Reference analog: internal/controller/main_test.go's envtest apiserver.
"""

import ssl
import time

import pytest

from runbooks_tpu.api.types import API_VERSION
from runbooks_tpu.k8s.client import AlreadyExists, Conflict, K8sClient, KubeConfig
from runbooks_tpu.k8s.httpfake import FakeApiServer


@pytest.fixture()
def server():
    with FakeApiServer() as s:
        yield s


@pytest.fixture()
def client(server):
    cfg = KubeConfig(server.url, ssl.create_default_context(), {})
    return K8sClient(cfg)


def model(name="m1", ns="default", **spec):
    return {"apiVersion": API_VERSION, "kind": "Model",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"image": "img", **spec}}


def test_create_get_update_delete_roundtrip(client, server):
    created = client.create(model())
    assert created["metadata"]["uid"]

    got = client.get(API_VERSION, "Model", "default", "m1")
    assert got["spec"]["image"] == "img"

    got["spec"]["image"] = "img2"
    updated = client.update(got)
    assert updated["spec"]["image"] == "img2"
    assert updated["metadata"]["generation"] == 2

    assert client.delete(API_VERSION, "Model", "default", "m1") is True
    assert client.delete(API_VERSION, "Model", "default", "m1") is False
    assert client.get(API_VERSION, "Model", "default", "m1") is None

    # URL shape: custom resources under /apis/{group}/{version}/namespaces/.
    paths = [p for (_, p, _, _) in server.requests]
    assert f"/apis/{API_VERSION}/namespaces/default/models/m1" in paths
    assert f"/apis/{API_VERSION}/namespaces/default/models" in paths


def test_core_v1_url_shape(client, server):
    client.create({"apiVersion": "v1", "kind": "ConfigMap",
                   "metadata": {"name": "cm", "namespace": "ns1"},
                   "data": {"k": "v"}})
    assert ("POST", "/api/v1/namespaces/ns1/configmaps", "",
            "application/json") in server.requests


def test_ssa_apply_field_manager_on_wire(client, server):
    client.apply(model(), "mgr-a")
    method, path, query, ctype = server.requests[-1]
    assert method == "PATCH"
    assert path.endswith("/models/m1")
    assert "fieldManager=mgr-a" in query and "force=true" in query
    assert ctype == "application/apply-patch+yaml"

    # Partial apply from a second manager merges rather than replaces.
    client.apply({"apiVersion": API_VERSION, "kind": "Model",
                  "metadata": {"name": "m1", "namespace": "default",
                               "annotations": {"a": "b"}}}, "mgr-b")
    got = client.get(API_VERSION, "Model", "default", "m1")
    assert got["spec"]["image"] == "img"
    assert got["metadata"]["annotations"]["a"] == "b"


def test_status_subresource_on_wire(client, server):
    client.create(model())
    obj = client.get(API_VERSION, "Model", "default", "m1")
    obj["status"] = {"ready": True}
    client.update_status(obj)
    method, path, _, _ = server.requests[-1]
    assert (method, path.rsplit("/", 1)[-1]) == ("PUT", "status")
    assert client.get(API_VERSION, "Model", "default",
                      "m1")["status"]["ready"] is True


def test_conflict_and_already_exists_mapping(client, server):
    client.create(model())
    with pytest.raises(AlreadyExists):
        client.create(model())

    stale = client.get(API_VERSION, "Model", "default", "m1")
    client.update(stale)  # bumps resourceVersion server-side
    stale["spec"]["image"] = "race"
    with pytest.raises(Conflict):
        client.update(stale)


def test_list_with_label_selector(client, server):
    obj = model("lab1")
    obj["metadata"]["labels"] = {"team": "a"}
    client.create(obj)
    client.create(model("lab2"))
    got = client.list(API_VERSION, "Model", namespace="default",
                      label_selector={"team": "a"})
    assert [o["metadata"]["name"] for o in got] == ["lab1"]
    # items get apiVersion/kind backfilled (lists omit them)
    assert got[0]["kind"] == "Model"


def test_watch_streams_events(client, server):
    sub = client.watch(API_VERSION, "Model", namespace="default")
    try:
        time.sleep(0.3)  # let the stream connect
        client.create(model("w1"))
        event = sub.poll(timeout=5.0)
        assert event is not None
        etype, obj = event
        assert etype == "ADDED"
        assert obj["metadata"]["name"] == "w1"

        client.delete(API_VERSION, "Model", "default", "w1")
        for _ in range(10):
            event = sub.poll(timeout=5.0)
            assert event is not None
            if event[0] == "DELETED":
                break
        else:
            raise AssertionError("no DELETED event")
    finally:
        # Without close(join=True) the reader thread outlives the fixture's
        # apiserver and prints `watch Model: reconnecting…` every 30 s for
        # the rest of the pytest run (VERDICT r5, Weak-5).
        sub.close(join=True)
