"""Quantized serving fast path: blockwise int8/int4 weight-only
quantization, the fused dequant-matmul, int8 KV cache, and the flash
cached-prefill route.

Oracles:
- pack/unpack is bit-exact; int8 round-trips exactly on power-of-two-scale
  grids; int4 error is bounded by half a quantization step per block.
- quantized_matmul == x @ dequantize(w) (scales-post-dot is algebraically
  exact, so only accumulation-order noise remains).
- a tiny quantized model's logits track the full-precision model and greedy
  decode agrees through the engine (weights AND int8 KV).
- the engine's prefill routes through the Pallas flash kernel when the
  query bucket is >= the flash min tile (kernel-count check like
  tests/test_flash_attention.py's) and matches the XLA path numerically.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import KVCache, forward, init_params
from runbooks_tpu.ops.quantization import (
    QuantizedArray,
    dequantize,
    pack_for_checkpoint,
    pack_int4,
    quantize,
    quantize_params,
    quantized_matmul,
    tree_weight_bytes,
    unpack_from_checkpoint,
    unpack_int4,
)
from runbooks_tpu.serve.engine import InferenceEngine, Request


def tiny_cfg(**over):
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32", **over)


# ---------------------------------------------------------------------------
# Pack / round-trip exactness
# ---------------------------------------------------------------------------

def test_int4_pack_unpack_exact():
    rng = np.random.default_rng(0)
    q = rng.integers(-7, 8, (6, 32, 10)).astype(np.int8)
    out = np.asarray(unpack_int4(pack_int4(jnp.asarray(q))))
    np.testing.assert_array_equal(out, q)


def test_int8_roundtrip_exact_on_grid():
    """Weights lying exactly on a power-of-two-scale int8 grid survive
    quantize->dequantize bit-exactly (127*2^e, /127, and q*2^e are all
    exact in f32)."""
    rng = np.random.default_rng(1)
    nb, bs, out = 3, 16, 8
    q = rng.integers(-127, 128, (nb, bs, out)).astype(np.float32)
    q[:, 0, :] = 127.0  # pin per-block amax so the scale is exactly 2^e
    scales = 2.0 ** rng.integers(-8, 2, (nb, 1, out)).astype(np.float32)
    w = (q * scales).reshape(nb * bs, out)
    qa = quantize(w, bits=8, block_size=bs)
    np.testing.assert_array_equal(np.asarray(dequantize(qa)), w)


def test_int4_error_bounded_by_half_step():
    rng = np.random.default_rng(2)
    w = rng.standard_normal((64, 16)).astype(np.float32)
    qa = quantize(w, bits=4, block_size=16)
    err = np.abs(np.asarray(dequantize(qa)) - w)
    # One quantization step per (block, channel) is amax/7; rounding keeps
    # each element within half a step (+ f32 noise).
    amax = np.abs(w.reshape(4, 16, 16)).max(axis=1, keepdims=True)
    step = np.broadcast_to(amax / 7.0, (4, 16, 16)).reshape(64, 16)
    assert (err <= step / 2 + 1e-6).all()


@pytest.mark.parametrize("bits", [8, 4])
def test_quantized_matmul_matches_dequant(bits):
    rng = np.random.default_rng(3)
    w = rng.standard_normal((64, 24)).astype(np.float32)
    x = rng.standard_normal((2, 5, 64)).astype(np.float32)
    qa = quantize(w, bits=bits, block_size=16)
    ref = np.asarray(x @ np.asarray(dequantize(qa)))
    got = np.asarray(quantized_matmul(jnp.asarray(x), qa, jnp.float32))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_quantize_params_structure_and_checkpoint_roundtrip():
    cfg = tiny_cfg()
    params = quantize_params(
        jax.tree.map(lambda x: x, init_params(cfg, jax.random.key(0))),
        "int4", block_size=32)
    attn = params["layers"]["attn"]
    mlp = params["layers"]["mlp"]
    for key in ("wq", "wk", "wv", "wo"):
        assert isinstance(attn[key], QuantizedArray), key
    for key in ("wi_gate", "wi_up", "wo"):
        assert isinstance(mlp[key], QuantizedArray), key
    # Norms/embeddings stay full precision.
    assert not isinstance(params["embed"], QuantizedArray)
    assert not isinstance(params["layers"]["ln1"]["scale"], QuantizedArray)
    # int4 shrinks total weight bytes well below half of f32.
    f32_bytes = tree_weight_bytes(init_params(cfg, jax.random.key(0)))
    assert tree_weight_bytes(params) < f32_bytes / 2
    # Checkpoint pack (plain dicts) -> unpack reconstructs QuantizedArrays
    # with identical contents and metadata.
    restored = unpack_from_checkpoint(pack_for_checkpoint(params))
    r = restored["layers"]["attn"]["wq"]
    assert isinstance(r, QuantizedArray)
    assert (r.bits, r.block_size) == (attn["wq"].bits, attn["wq"].block_size)
    np.testing.assert_array_equal(np.asarray(r.values),
                                  np.asarray(attn["wq"].values))


# ---------------------------------------------------------------------------
# Model-level parity
# ---------------------------------------------------------------------------

def test_quantized_logits_parity_tiny_model():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    q8 = quantize_params(jax.tree.map(lambda x: x, params), "int8",
                         block_size=32)
    toks = jnp.asarray([[5, 9, 17, 3, 2, 44, 7, 101]], jnp.int32)
    ref, _ = forward(cfg, params, toks)
    got, _ = forward(cfg, q8, toks)
    scale = float(jnp.max(jnp.abs(ref)))
    assert float(jnp.max(jnp.abs(ref - got))) < 0.05 * max(scale, 1.0)
    assert (jnp.argmax(ref[:, -1], -1) == jnp.argmax(got[:, -1], -1)).all()


def test_quantized_engine_greedy_matches_bf16_weights():
    """int8-weight + int8-KV engine greedy decode agrees with the
    full-precision engine on short prompts (the acceptance parity check —
    short rollouts; tiny random models have near-tied logits further out)."""
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    q8 = quantize_params(jax.tree.map(lambda x: x, params), "int8",
                         block_size=32)
    prompts = [[5, 9, 17], [3, 4, 5, 6, 7, 8, 9, 10]]

    def run(p, quantize_kv):
        eng = InferenceEngine(cfg, p, max_slots=2, quantize_kv=quantize_kv)
        reqs = [Request(prompt_tokens=pr, max_tokens=4, temperature=0.0)
                for pr in prompts]
        eng.generate(reqs)
        return [r.output_tokens for r in reqs]

    assert run(params, False) == run(q8, True)


def test_int8_kv_decode_greedy_agreement():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    f32 = InferenceEngine(cfg, params, max_slots=2, quantize_kv=False)
    i8 = InferenceEngine(cfg, params, max_slots=2, quantize_kv=True)
    assert i8.cache.quantized and i8.cache.k.dtype == jnp.int8
    assert not f32.cache.quantized
    for prompt in ([5, 9, 17], [42]):
        a = Request(prompt_tokens=list(prompt), max_tokens=4,
                    temperature=0.0)
        b = Request(prompt_tokens=list(prompt), max_tokens=4,
                    temperature=0.0)
        f32.generate([a])
        i8.generate([b])
        assert a.output_tokens == b.output_tokens, prompt


def test_int8_kv_halves_cache_bytes():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    f32 = InferenceEngine(cfg, params, max_slots=2)
    i8 = InferenceEngine(cfg, params, max_slots=2, quantize_kv=True)
    full = f32.cache.k.nbytes + f32.cache.v.nbytes
    packed = (i8.cache.k.nbytes + i8.cache.v.nbytes
              + i8.cache.k_scale.nbytes + i8.cache.v_scale.nbytes)
    # int8 + one f32 scale per head_dim=16 row: 16 bytes -> 4+... well under
    # 60% of the f32 cache; at bf16/head_dim=128 serving shapes it is ~51%.
    assert packed < 0.6 * full


# ---------------------------------------------------------------------------
# Flash cached-prefill
# ---------------------------------------------------------------------------

def _count_pallas_calls(jaxpr, n=0):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            n += 1
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):
                n = _count_pallas_calls(v.jaxpr, n)
            elif hasattr(v, "eqns"):
                n = _count_pallas_calls(v, n)
    return n


def test_flash_cached_prefill_matches_xla_and_uses_kernel():
    cfg_x = tiny_cfg()
    cfg_f = tiny_cfg(attention_impl="flash", flash_block_q=16,
                     flash_block_k=16)
    params = init_params(cfg_x, jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (2, 32), 1, 128)

    # Scalar-index chunked prefill.
    ref, ref_cache = forward(cfg_x, params, toks,
                             cache=KVCache.create(cfg_x, 2, 64))
    got, got_cache = forward(cfg_f, params, toks,
                             cache=KVCache.create(cfg_f, 2, 64))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)

    # Position-scatter mode under a bucketed view (the engine's layout).
    pos = jnp.broadcast_to(jnp.arange(32, dtype=jnp.int32)[None], (2, 32))
    ref2, _ = forward(cfg_x, params, toks, positions=pos,
                      cache=KVCache.create(cfg_x, 2, 65), cache_view=48)
    got2, _ = forward(cfg_f, params, toks, positions=pos,
                      cache=KVCache.create(cfg_f, 2, 65), cache_view=48)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(ref2),
                               rtol=2e-4, atol=2e-4)

    # The kernel is actually on the cached-prefill path; decode (s=1)
    # stays XLA.
    def prefill(p, t):
        return forward(cfg_f, p, t, cache=KVCache.create(cfg_f, 2, 64))[0]

    def decode(p, t):
        return forward(cfg_f, p, t, cache=KVCache.create(cfg_f, 2, 64))[0]

    assert _count_pallas_calls(
        jax.make_jaxpr(prefill)(params, toks).jaxpr) >= 1
    assert _count_pallas_calls(
        jax.make_jaxpr(decode)(params, toks[:, :1]).jaxpr) == 0


def test_engine_prefill_routes_through_flash_kernel():
    """The ENGINE's jitted prefill exercises the flash kernel for
    long-bucket prefills (the VERDICT Missing-4 acceptance check): trace
    the exact function the engine dispatches and count pallas calls."""
    cfg = tiny_cfg(attention_impl="flash", flash_block_q=16,
                   flash_block_k=16)
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2)

    rows, bucket = 1, 32
    tokens = jnp.zeros((rows, bucket), jnp.int32)
    positions = jnp.broadcast_to(
        jnp.arange(bucket, dtype=jnp.int32)[None], (rows, bucket))
    args = (engine.params, engine.cache, tokens, positions,
            jnp.zeros(rows, jnp.int32), jnp.full(rows, bucket - 1,
                                                 jnp.int32),
            jax.random.key(0), jnp.zeros(rows, jnp.float32),
            jnp.zeros(rows, jnp.int32), jnp.ones(rows, jnp.float32))
    jaxpr = jax.make_jaxpr(engine._prefill)(*args)
    assert _count_pallas_calls(jaxpr.jaxpr) >= 1

    # And end-to-end: the flash-prefill engine produces the same greedy
    # tokens as the XLA engine.
    plain = InferenceEngine(tiny_cfg(), params, max_slots=2)
    for eng in (engine, plain):
        eng.reset()
    prompt = list(range(1, 21))  # 20 tokens -> 32-bucket >= flash min tile
    outs = []
    for eng in (engine, plain):
        r = Request(prompt_tokens=list(prompt), max_tokens=6,
                    temperature=0.0)
        eng.generate([r])
        outs.append(r.output_tokens)
    assert outs[0] == outs[1]
