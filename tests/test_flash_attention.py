"""Flash-attention kernel vs the XLA reference attention (the numerical
oracle), forward and backward, in Pallas interpreter mode on CPU."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.ops.attention import dot_product_attention, make_attention_mask
from runbooks_tpu.ops.flash_attention import flash_attention


def make_inputs(b=2, sq=128, sk=128, h=2, d=32, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, sq, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, sk, h, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, sk, h, d), jnp.float32)
    q_pos = jnp.broadcast_to(jnp.arange(sq, dtype=jnp.int32)[None], (b, sq))
    kv_pos = jnp.broadcast_to(jnp.arange(sk, dtype=jnp.int32)[None], (b, sk))
    return q, k, v, q_pos, kv_pos


def oracle(q, k, v, q_pos, kv_pos, q_seg=None, kv_seg=None, causal=True):
    mask = make_attention_mask(q_pos, kv_pos, q_seg, kv_seg, causal=causal)
    return dot_product_attention(q, k, v, mask=mask)


@pytest.mark.parametrize("block", [64, 128])
def test_forward_matches_oracle_causal(block):
    q, k, v, q_pos, kv_pos = make_inputs()
    ref = oracle(q, k, v, q_pos, kv_pos)
    got = flash_attention(q, k, v, q_pos, kv_pos, None, None, True, None,
                          block, block)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_forward_non_divisible_seq():
    q, k, v, q_pos, kv_pos = make_inputs(sq=100, sk=100)
    ref = oracle(q, k, v, q_pos, kv_pos)
    got = flash_attention(q, k, v, q_pos, kv_pos, None, None, True, None,
                          64, 64)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_forward_with_segments():
    b, s = 2, 128
    q, k, v, q_pos, kv_pos = make_inputs(sq=s, sk=s)
    # Two packed docs + padding tail; positions restart per segment.
    seg = np.ones((b, s), np.int32)
    seg[:, 48:96] = 2
    seg[:, 96:] = 0
    pos = np.concatenate([np.arange(48), np.arange(48), np.arange(32)])
    pos = np.broadcast_to(pos, (b, s)).astype(np.int32)
    seg, pos = jnp.asarray(seg), jnp.asarray(pos)
    ref = oracle(q, k, v, pos, pos, seg, seg)
    got = flash_attention(q, k, v, pos, pos, seg, seg, True, None, 64, 64)
    # Padding rows (seg 0) are fully masked: oracle zeroes them; flash
    # zeroes them too via the l==0 guard.
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_noncausal_with_padding_keys():
    # Regression: with causal=False, zero-padded keys (sk not a block
    # multiple) must still be masked out of the softmax denominator.
    q, k, v, q_pos, kv_pos = make_inputs(sq=100, sk=100)
    ref = oracle(q, k, v, q_pos, kv_pos, causal=False)
    got = flash_attention(q, k, v, q_pos, kv_pos, None, None, False, None,
                          64, 64)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_forward_bf16_close():
    q, k, v, q_pos, kv_pos = make_inputs()
    ref = oracle(q, k, v, q_pos, kv_pos)
    got = flash_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                          v.astype(jnp.bfloat16), q_pos, kv_pos, None, None)
    assert float(jnp.max(jnp.abs(got.astype(jnp.float32) - ref))) < 0.05


def test_gqa_forward_and_grads():
    b, s, h, kv_h, d = 1, 64, 4, 2, 16
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (b, s, h, d), jnp.float32)
    k = jax.random.normal(ks[1], (b, s, kv_h, d), jnp.float32)
    v = jax.random.normal(ks[2], (b, s, kv_h, d), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, pos, pos, None, None, True, None, 32, 32)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(oracle(q, k, v, pos, pos)))

    np.testing.assert_allclose(loss_flash(q, k, v), loss_ref(q, k, v),
                               rtol=1e-5)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def test_gradients_match_oracle():
    q, k, v, q_pos, kv_pos = make_inputs(b=1, sq=96, sk=96, h=2, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, q_pos, kv_pos, None, None, True, None,
                            32, 32)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = oracle(q, k, v, q_pos, kv_pos)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def test_gradients_with_segments():
    b, s = 1, 64
    q, k, v, _, _ = make_inputs(b=b, sq=s, sk=s, h=2, d=16, seed=3)
    seg = np.ones((b, s), np.int32)
    seg[:, 40:] = 0  # padding tail
    pos = np.broadcast_to(np.arange(s), (b, s)).astype(np.int32)
    seg, pos = jnp.asarray(seg), jnp.asarray(pos)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, pos, pos, seg, seg, True, None, 32, 32)
        return jnp.sum(o)

    def loss_ref(q, k, v):
        return jnp.sum(oracle(q, k, v, pos, pos, seg, seg))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def test_gradients_kv_longer_than_q_causal():
    """sk > sq with causal block skip: kv blocks entirely past the last q
    block must produce dk/dv == 0, not stale scratch from the previous
    block (regression: _first_valid_q lacked the num_q-1 clamp)."""
    q, k, v, q_pos, kv_pos = make_inputs(b=1, sq=32, sk=128, h=2, d=16)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, q_pos, kv_pos, None, None, True, None,
                            32, 32)
        return jnp.sum(o * jnp.cos(o))

    def loss_ref(q, k, v):
        o = oracle(q, k, v, q_pos, kv_pos)
        return jnp.sum(o * jnp.cos(o))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    # Keys at positions > max q position get exactly zero gradient.
    np.testing.assert_array_equal(np.asarray(gf[1][:, 32:]), 0.0)
    np.testing.assert_array_equal(np.asarray(gf[2][:, 32:]), 0.0)
    for a, b_, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(a, b_, rtol=1e-4, atol=1e-4,
                                   err_msg=f"d{name}")


def _count_pallas_calls(jaxpr, n=0):
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "pallas_call":
            n += 1
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):
                n = _count_pallas_calls(v.jaxpr, n)
            elif hasattr(v, "eqns"):
                n = _count_pallas_calls(v, n)
    return n


def test_save_attn_out_skips_fwd_kernel_recompute():
    """remat_policy="save_attn_out" must eliminate the O(s^2) fwd-kernel
    re-run in the backward pass: the kernel's residuals (out, lse) are
    hoisted to the caller's trace level (ops/flash_attention.py) exactly so
    the checkpoint policy can save them. nothing_saveable: fwd x2 (primal +
    recompute) + dq + dkv = 4 pallas calls; save_attn_out: 3."""
    import dataclasses

    from runbooks_tpu.models.config import get_config
    from runbooks_tpu.models.transformer import forward, init_params

    base = dataclasses.replace(
        get_config("debug"), attention_impl="flash",
        flash_block_q=64, flash_block_k=64)
    tokens = jnp.zeros((1, 128), jnp.int32)
    counts = {}
    for policy in ("nothing_saveable", "save_attn_out"):
        cfg = dataclasses.replace(base, remat_policy=policy)
        params = init_params(cfg, jax.random.key(0))

        def loss(p, cfg=cfg):
            logits, _ = forward(cfg, p, tokens, remat=True)
            return jnp.mean(logits)

        jaxpr = jax.make_jaxpr(jax.grad(loss))(params)
        counts[policy] = _count_pallas_calls(jaxpr.jaxpr)
    assert counts["nothing_saveable"] == 4, counts
    assert counts["save_attn_out"] == 3, counts
