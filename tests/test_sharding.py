"""Sharding-rule unit tests, incl. the regression for constraints under
jax.set_mesh (they must bind to the context mesh, not silently no-op)."""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh
from runbooks_tpu.parallel.sharding import (
    logical_to_spec,
    spec_for_array,
    with_logical_constraint,
)


def test_logical_to_spec_dedups_mesh_axes():
    # "batch" uses (data, fsdp); a second logical axis mapping to fsdp must
    # not reuse it within one spec.
    spec = logical_to_spec(("batch", "embed"))
    assert spec == P(("data", "fsdp"), None)


def test_spec_for_array_drops_nondivisible_axes():
    mesh = make_mesh(MeshConfig(data=1, fsdp=8, sequence=1, tensor=1))
    # dim 4 not divisible by fsdp=8 -> replicated
    assert spec_for_array((4, 16), ("embed", None), mesh) == P(None, None)
    assert spec_for_array((16, 4), ("embed", None), mesh) == P("fsdp", None)


def test_constraint_applies_under_set_mesh():
    mesh = make_mesh(MeshConfig(data=2, fsdp=4, sequence=1, tensor=1))

    @jax.jit
    def f(x):
        return with_logical_constraint(x, ("batch", "seq"))

    with jax.set_mesh(mesh):
        y = f(jnp.zeros((8, 16)))
    # Regression: under set_mesh this used to silently return the input
    # unconstrained (thread_resources is not populated by set_mesh).
    assert y.sharding.spec[0] == ("data", "fsdp"), y.sharding.spec
    shard_shapes = {s.data.shape for s in y.addressable_shards}
    assert shard_shapes == {(1, 16)}, shard_shapes


def test_constraint_noop_outside_mesh():
    x = jnp.zeros((8, 16))
    y = with_logical_constraint(x, ("batch", None))
    assert y.shape == x.shape
