"""Trainer workload tests: params.json -> training -> artifacts, and resume."""

import json
import os

import pytest

from runbooks_tpu.parallel.mesh import MeshConfig
from runbooks_tpu.train.lora import LoraConfig
from runbooks_tpu.train.optimizer import OptimizerConfig
from runbooks_tpu.train.trainer import TrainJobConfig, run_training
from runbooks_tpu.utils import contract


def job(tmp_path, steps=6, data_path=None, **kw):
    return TrainJobConfig(
        model="debug", model_overrides={"dtype": "float32"},
        mesh=MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=0,
                                  total_steps=100, schedule="constant"),
        batch_size=4, seq_len=32, steps=steps,
        checkpoint_every=3, log_every=2,
        artifacts_dir=str(tmp_path), data_path=data_path, **kw,
    )


def test_training_writes_artifacts_and_metrics(tmp_path):
    summary = run_training(job(tmp_path))
    assert summary["final_loss"] is not None
    assert os.path.exists(tmp_path / "metrics.json")
    assert os.path.isdir(tmp_path / "checkpoints")
    steps = os.listdir(tmp_path / "checkpoints")
    assert "6" in steps


@pytest.mark.slow
def test_training_resumes_from_checkpoint(tmp_path):
    run_training(job(tmp_path, steps=3))
    # Second run with more steps resumes at 3, trains to 6.
    summary = run_training(job(tmp_path, steps=6))
    assert summary["history"][0]["step"] > 3 or summary["history"][0]["step"] == 4


def test_trainer_from_params_json(tmp_path):
    params = {
        "model": "debug", "steps": 4, "batch_size": 2, "seq_len": 16,
        "mesh_data": 1, "mesh_fsdp": 8, "mesh_tensor": 1,
        "learning_rate": 1e-3, "checkpoint_every": 10,
        "artifacts_dir": str(tmp_path),
        "model_overrides": {"dtype": "float32"},
    }
    j = TrainJobConfig.from_params(params)
    assert j.mesh.fsdp == 8 and j.steps == 4
    summary = run_training(j)
    assert summary["steps"] == 4


def test_trainer_with_jsonl_data_and_lora(tmp_path):
    data = tmp_path / "data"
    os.makedirs(data)
    with open(data / "docs.jsonl", "w") as f:
        for i in range(30):
            f.write(json.dumps({"text": f"document number {i} " * 3}) + "\n")
    summary = run_training(job(
        tmp_path, steps=4, data_path=str(data), lora=LoraConfig(rank=2)))
    assert summary["lora"] is True
    assert os.path.exists(tmp_path / "lora.json")


def test_from_params_accumulate_aliases_and_string_ints():
    # camelCase (reference spec style) and env-lowercased spellings both
    # land on accumulate_steps, and YAML-quoted ints coerce — a
    # controller-validated spec must not silently drop accumulation or
    # TypeError mid-job.
    j = TrainJobConfig.from_params({"accumulateSteps": "8",
                                    "batch_size": "64"})
    assert j.accumulate_steps == 8 and j.batch_size == 64
    j = TrainJobConfig.from_params({"accumulatesteps": 4})
    assert j.accumulate_steps == 4
    j = TrainJobConfig.from_params({"accumulate_steps": 2,
                                    "accumulateSteps": 16})
    assert j.accumulate_steps == 2  # snake_case wins


def test_trainer_fast_path_accum_chunk_prefetch(tmp_path):
    # The whole training fast path at once: 2-way grad accumulation,
    # chunked fused CE, and the async prefetcher (default depth 2).
    summary = run_training(job(
        tmp_path, steps=4, accumulate_steps=2, loss_chunk=16))
    assert summary["final_loss"] is not None
    assert summary["accumulate_steps"] == 2
    # Compile time is reported separately and excluded from the
    # steady-state tokens/sec window (the first-step reset).
    assert summary["compile_time_s"] is not None
    assert summary["compile_time_s"] > 0
    assert summary["history"][0]["compile_time_s"] == round(
        summary["compile_time_s"], 2)
    assert summary["tokens_per_sec"] > 0


def test_trainer_accum_must_divide_batch(tmp_path):
    import pytest

    with pytest.raises(ValueError, match="divide"):
        run_training(job(tmp_path, steps=2, accumulate_steps=3))


def test_trainer_rejects_oversized_tokenizer_vocab(tmp_path):
    import json as _json

    import pytest

    data = tmp_path / "data"
    os.makedirs(data)
    with open(data / "docs.jsonl", "w") as f:
        f.write(_json.dumps({"text": "hello"}) + "\n")
    # Byte tokenizer vocab is 258 > the overridden model vocab of 128:
    # must raise (not assert — python -O would strip an assert).
    import dataclasses

    small_vocab = dataclasses.replace(
        job(tmp_path, steps=1, data_path=str(data)),
        model_overrides={"dtype": "float32", "vocab_size": 128})
    with pytest.raises(ValueError, match="vocab"):
        run_training(small_vocab)


def test_params_env_roundtrip(monkeypatch):
    monkeypatch.setenv("PARAM_STEPS", "7")
    monkeypatch.setenv("PARAM_MODEL", "debug")
    params = contract.load_params(path="/nonexistent/params.json")
    assert params["steps"] == 7
    assert params["model"] == "debug"
    env = contract.params_to_env({"steps": 7, "model": "debug"})
    assert env == {"PARAM_STEPS": "7", "PARAM_MODEL": "debug"}
