"""HF weight-conversion parity: build tiny HF models (random init, no
downloads), convert their state dicts, and compare logits between the HF
torch implementation and our JAX forward. This pins the architecture
semantics (RoPE convention, fused-QKV unfusing, OPT position offset,
parallel-block wiring) against the de-facto reference implementations."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from runbooks_tpu.models.config import ModelConfig
from runbooks_tpu.models.convert import convert
from runbooks_tpu.models.transformer import forward


def compare(cfg, hf_model, tokens, atol=2e-3):
    hf_model.eval()
    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(np.asarray(tokens))).logits.numpy()
    sd = {k: v.float().numpy() for k, v in hf_model.state_dict().items()}
    params = convert(cfg, sd)
    params = jax.tree.map(jnp.asarray, params)
    ours, _ = forward(cfg, params, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(ours), hf_logits, atol=atol,
                               rtol=2e-3)


def test_llama_parity():
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        attention_bias=False, tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = LlamaForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="llama-test", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32")
    tokens = np.random.default_rng(0).integers(0, 128, (2, 12))
    compare(cfg, hf, tokens)


@pytest.mark.parametrize("mqa", [True, False])
def test_falcon_parity(mqa):
    from transformers import FalconConfig, FalconForCausalLM

    hf_cfg = FalconConfig(
        vocab_size=128, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=None if mqa else 2,
        multi_query=mqa, new_decoder_architecture=not mqa,
        parallel_attn=True, bias=False, alibi=False)
    torch.manual_seed(0)
    hf = FalconForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="falcon-test", vocab_size=128, hidden_size=64,
        intermediate_size=256, num_layers=2, num_heads=4,
        num_kv_heads=1 if mqa else 2, head_dim=16, max_seq_len=64,
        norm_type="layernorm", gated_mlp=False, activation="gelu",
        position_type="rope", parallel_block=True,
        shared_layer_norm=mqa, tie_embeddings=True, dtype="float32")
    tokens = np.random.default_rng(1).integers(0, 128, (2, 10))
    compare(cfg, hf, tokens)


def test_opt_parity():
    from transformers import OPTConfig, OPTForCausalLM

    hf_cfg = OPTConfig(
        vocab_size=128, hidden_size=64, ffn_dim=128, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        do_layer_norm_before=True, word_embed_proj_dim=64,
        tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = OPTForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="opt-test", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=4,
        head_dim=16, max_seq_len=64, norm_type="layernorm", gated_mlp=False,
        activation="relu", position_type="learned", attn_bias=True,
        mlp_bias=True, tie_embeddings=True, dtype="float32")
    tokens = np.random.default_rng(2).integers(0, 128, (2, 11))
    compare(cfg, hf, tokens)


def test_mixtral_parity():
    """HF Mixtral (llama attention + sparse MoE FFN) vs our MoE path. High
    capacity factor => no token drops, so the top-2 routed output is exact
    (HF routes densely per token with no capacity)."""
    from transformers import MixtralConfig, MixtralForCausalLM

    hf_cfg = MixtralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rms_norm_eps=1e-5, rope_theta=10000.0,
        num_local_experts=4, num_experts_per_tok=2,
        tie_word_embeddings=False)
    torch.manual_seed(0)
    hf = MixtralForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="mixtral-test", vocab_size=128, hidden_size=64,
        intermediate_size=96, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32",
        moe_num_experts=4, moe_top_k=2, moe_capacity_factor=8.0)
    tokens = np.random.default_rng(2).integers(0, 128, (2, 12))
    compare(cfg, hf, tokens)


def test_gemma_parity():
    """Gemma: llama keys + (1+w) RMSNorm + GeGLU + scaled tied embeddings."""
    from transformers import GemmaConfig, GemmaForCausalLM

    hf_cfg = GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        hidden_act="gelu_pytorch_tanh", tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = GemmaForCausalLM(hf_cfg)
    cfg = ModelConfig(
        name="gemma-test", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=1,
        head_dim=16, max_seq_len=64, norm_eps=1e-6, activation="gelu",
        tie_embeddings=True, embed_scale=True, dtype="float32")
    tokens = np.random.default_rng(3).integers(0, 128, (2, 12))
    compare(cfg, hf, tokens)


def test_gpt2_parity():
    """GPT-2: Conv1D (no transpose), fused qkv, learned positions."""
    from transformers import GPT2Config, GPT2LMHeadModel

    hf_cfg = GPT2Config(
        vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=64,
        activation_function="gelu_new")
    torch.manual_seed(0)
    hf = GPT2LMHeadModel(hf_cfg)
    cfg = ModelConfig(
        name="gpt2-test", vocab_size=128, hidden_size=64,
        intermediate_size=256, num_layers=2, num_heads=4, num_kv_heads=4,
        head_dim=16, max_seq_len=64, norm_type="layernorm", gated_mlp=False,
        activation="gelu", position_type="learned", attn_bias=True,
        mlp_bias=True, tie_embeddings=True, dtype="float32")
    tokens = np.random.default_rng(4).integers(0, 128, (2, 10))
    compare(cfg, hf, tokens)
