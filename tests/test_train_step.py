"""Sharded train-step tests on the 8-device virtual CPU mesh.

Validates the full DP/FSDP/TP/SP layouts compile and execute, that loss
decreases on an overfit batch, and that different mesh layouts produce the
same numerics (the sharding must not change the math).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh
from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
from runbooks_tpu.train.step import create_train_state, make_train_step


def tiny_cfg():
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=32, dtype="float32",
    )


def make_batch(cfg, batch=8, seq=16, seed=0):
    rng = jax.random.key(seed)
    tokens = jax.random.randint(rng, (batch, seq + 1), 0, cfg.vocab_size)
    return {
        "tokens": tokens[:, :-1],
        "targets": tokens[:, 1:],
        "loss_mask": jnp.ones((batch, seq), jnp.float32),
    }


def run_steps(mesh_config, n_steps=3, seed=0):
    cfg = tiny_cfg()
    mesh = make_mesh(mesh_config)
    opt = make_optimizer(OptimizerConfig(learning_rate=1e-2, warmup_steps=0,
                                         total_steps=100, schedule="constant"))
    state, shardings = create_train_state(cfg, opt, mesh, jax.random.key(seed))
    step = make_train_step(cfg, opt, mesh, shardings)
    batch = make_batch(cfg)
    losses = []
    with jax.set_mesh(mesh):
        for _ in range(n_steps):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    return losses, state


MESHES = [
    MeshConfig(data=8, fsdp=1, sequence=1, tensor=1),
    MeshConfig(data=1, fsdp=8, sequence=1, tensor=1),
    MeshConfig(data=1, fsdp=1, sequence=1, tensor=8),
    MeshConfig(data=2, fsdp=2, sequence=1, tensor=2),
    MeshConfig(data=1, fsdp=2, sequence=2, tensor=2),
]


@pytest.mark.parametrize("mesh_config", MESHES, ids=lambda m: f"d{m.data}f{m.fsdp}s{m.sequence}t{m.tensor}")
def test_train_step_runs_and_learns(mesh_config):
    losses, _ = run_steps(mesh_config, n_steps=4)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


def test_mesh_layouts_agree_numerically():
    # Green since the layout-invariant init fix (partitionable-threefry
    # scope in create_train_state — the sharding must not change the
    # values the init materializes, whatever the mesh layout).
    ref_losses, _ = run_steps(MeshConfig(data=8, fsdp=1, sequence=1, tensor=1))
    for mc in [MeshConfig(data=1, fsdp=8, sequence=1, tensor=1),
               MeshConfig(data=2, fsdp=2, sequence=1, tensor=2)]:
        losses, _ = run_steps(mc)
        np.testing.assert_allclose(losses, ref_losses, rtol=1e-4, atol=1e-5)


def test_bf16_masters_and_mu_dtype():
    # The state-memory levers (BENCH_NOTES r3: f32 masters + adam moments
    # are the 5 GB forcing full remat): bf16 master params + bf16 mu must
    # produce a train step that runs, shards, and still learns.
    cfg = dataclasses.replace(tiny_cfg(), param_dtype="bfloat16",
                              dtype="bfloat16")
    mesh = make_mesh(MeshConfig(data=1, fsdp=8, sequence=1, tensor=1))
    opt = make_optimizer(OptimizerConfig(
        learning_rate=1e-2, warmup_steps=0, total_steps=100,
        schedule="constant", mu_dtype="bfloat16"))
    state, shardings = create_train_state(cfg, opt, mesh, jax.random.key(0))

    assert all(x.dtype == jnp.bfloat16
               for x in jax.tree.leaves(state.params))
    adam_state = state.opt_state[1][0]  # (clip, adamw(scale_by_adam, ...))
    assert all(x.dtype == jnp.bfloat16 for x in jax.tree.leaves(adam_state.mu))

    step = make_train_step(cfg, opt, mesh, shardings)
    batch = make_batch(cfg)
    losses = []
    with jax.set_mesh(mesh):
        for _ in range(4):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0], losses


def test_fsdp_actually_shards_params():
    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(data=1, fsdp=8, sequence=1, tensor=1))
    opt = make_optimizer(OptimizerConfig())
    state, _ = create_train_state(cfg, opt, mesh, jax.random.key(0))
    # embed is [vocab=128, embed=64]: fsdp shards the embed axis of layer
    # matrices; check a layer matrix is actually distributed.
    wq = state.params["layers"]["attn"]["wq"]  # [L, h=64, q_dim=64]
    shard_shapes = {s.data.shape for s in wq.addressable_shards}
    assert shard_shapes == {(2, 8, 64)}, shard_shapes
