"""Multi-process jax.distributed rendezvous test (SURVEY §4 implication (d)).

Spawns N local processes with the EXACT env shape the operator's fan-out
injects into slice pods (cloud/resources.py:distributed_env — coordinator
address, process count, pod-index-derived process id), then asserts the
runtime forms, cross-process collectives work, and a global-mesh train step
runs. This is the piece the reference never had (no trainer rendezvous at
all — SURVEY §2a) and round 1 never executed.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from runbooks_tpu.cloud.resources import (
    JAX_COORDINATOR_PORT,
    distributed_env,
    parse_tpu,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_distributed_env_shape():
    """The operator injects exactly what distributed.initialize consumes."""
    slice_ = parse_tpu({"type": "v5e", "topology": "4x4"})  # 2-host slice
    env = distributed_env("job", "svc", "ns", slice_)
    by_name = {e["name"]: e for e in env}
    assert by_name["JAX_COORDINATOR_ADDRESS"]["value"] == (
        f"job-0.svc.ns.svc.cluster.local:{JAX_COORDINATOR_PORT}")
    assert by_name["JAX_NUM_PROCESSES"]["value"] == str(slice_.hosts)
    # Process id comes from the indexed-Job completion index annotation.
    ref = by_name["JAX_PROCESS_ID"]["valueFrom"]["fieldRef"]["fieldPath"]
    assert "job-completion-index" in ref


@pytest.mark.slow
def test_two_process_rendezvous_psum_and_train_step(tmp_path):
    nproc = 2
    port = _free_port()
    procs = []
    for pid in range(nproc):
        env = dict(os.environ)
        # The operator's env contract, localhost flavor (the fieldRef that
        # resolves the pod index becomes a literal process id here).
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = str(nproc)
        env["JAX_PROCESS_ID"] = str(pid)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        procs.append(subprocess.Popen(
            [sys.executable, os.path.join(os.path.dirname(__file__),
                                          "distworker.py")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"worker failed:\n{err[-2000:]}"
        outs.append(json.loads(out.strip().splitlines()[-1]))

    assert all(o["ok"] for o in outs)
    # 2 processes x 2 virtual devices each = 4 global devices.
    assert all(o["world_devices"] == 4 for o in outs)
    assert sorted(o["process"] for o in outs) == [0, 1]
    assert [o["primary"] for o in sorted(outs, key=lambda o: o["process"])] \
        == [True, False]
    # SPMD: every process computes the identical global loss.
    assert outs[0]["loss"] == outs[1]["loss"]
