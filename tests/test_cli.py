"""CLI tests: apply/get/delete/run against the fake cluster, plus the upload
handshake client-side flow."""

import os
import threading
import time

import pytest

from runbooks_tpu.api.types import API_VERSION, Model
from runbooks_tpu.cli import main as cli
from runbooks_tpu.k8s import objects as ko
from runbooks_tpu.k8s.fake import FakeCluster


@pytest.fixture()
def fake(monkeypatch):
    cluster = FakeCluster()
    monkeypatch.setattr(cli, "make_client", lambda args: cluster)
    return cluster


def write_manifests(tmp_path):
    (tmp_path / "stack.yaml").write_text("""
apiVersion: runbooks-tpu.dev/v1
kind: Server
metadata: {name: srv}
spec: {image: s, model: {name: m1}}
---
apiVersion: runbooks-tpu.dev/v1
kind: Model
metadata: {name: m1}
spec: {image: trainer}
---
apiVersion: v1
kind: ConfigMap
metadata: {name: ignored}
""")
    return str(tmp_path / "stack.yaml")


def test_apply_get_delete(tmp_path, fake, capsys):
    path = write_manifests(tmp_path)
    assert cli.main(["apply", "-f", path]) == 0
    out = capsys.readouterr().out
    # dependency-friendly order: Model before Server
    assert out.index("Model/m1") < out.index("Server/srv")
    assert fake.get(API_VERSION, "Model", "default", "m1") is not None
    assert fake.get(API_VERSION, "Server", "default", "srv") is not None

    assert cli.main(["get", ""]) == 0
    out = capsys.readouterr().out
    assert "models/m1" in out and "servers/srv" in out

    assert cli.main(["get", "models/m1"]) == 0
    out = capsys.readouterr().out
    assert "models/m1" in out and "servers/srv" not in out

    assert cli.main(["delete", "models/m1"]) == 0
    assert fake.get(API_VERSION, "Model", "default", "m1") is None
    assert cli.main(["delete", "-f", path]) == 0
    assert fake.get(API_VERSION, "Server", "default", "srv") is None


def test_run_auto_increment(tmp_path, fake):
    (tmp_path / "job.yaml").write_text("""
apiVersion: runbooks-tpu.dev/v1
kind: Model
metadata: {name: exp}
spec: {image: trainer}
""")
    fake.create(Model.new("exp").obj)
    fake.create(Model.new("exp-3").obj)

    def make_ready_soon():
        for _ in range(100):
            obj = fake.get(API_VERSION, "Model", "default", "exp-4")
            if obj:
                obj.setdefault("status", {})["ready"] = True
                fake.update_status(obj)
                return
            time.sleep(0.05)

    t = threading.Thread(target=make_ready_soon, daemon=True)
    t.start()
    rc = cli.main(["run", "-f", str(tmp_path / "job.yaml"), "-i",
                   "--timeout", "10"])
    assert rc == 0
    assert fake.get(API_VERSION, "Model", "default", "exp-4") is not None


def test_upload_build_context(tmp_path, fake):
    from runbooks_tpu.utils.upload import upload_build_context

    src = tmp_path / "ctx"
    src.mkdir()
    (src / "Dockerfile").write_text("FROM scratch\n")
    (src / "train.py").write_text("print('hi')\n")

    obj = Model.new("up", spec={"build": {"upload": {}}}).obj
    fake.create(obj)

    uploaded = {}

    def fake_controller():
        # Play the build reconciler's part: watch for the requestID, publish
        # a signed URL.
        for _ in range(200):
            cur = fake.get(API_VERSION, "Model", "default", "up")
            req_id = ko.deep_get(cur, "spec", "build", "upload", "requestID")
            if req_id:
                ko.deep_set(cur, {"signedURL": "http://127.0.0.1:1/unused",
                                  "requestID": req_id,
                                  "expiration": int(time.time()) + 300},
                            "status", "buildUpload")
                fake.update_status(cur)
                return
            time.sleep(0.02)

    t = threading.Thread(target=fake_controller, daemon=True)
    t.start()

    import runbooks_tpu.utils.upload as up

    def fake_put(url, data, md5):
        uploaded["url"], uploaded["bytes"], uploaded["md5"] = \
            url, len(data), md5

    orig = up.put_signed_url
    up.put_signed_url = fake_put
    try:
        result = upload_build_context(fake, obj, str(src), timeout_s=10)
    finally:
        up.put_signed_url = orig

    assert uploaded["bytes"] > 0
    assert ko.deep_get(result, "spec", "build", "upload", "md5checksum") == \
        uploaded["md5"]
    assert ko.annotations(result).get(
        "runbooks-tpu.dev/upload-timestamp")


def test_upload_requires_dockerfile(tmp_path):
    from runbooks_tpu.utils.upload import prepare_image_tarball

    with pytest.raises(FileNotFoundError):
        prepare_image_tarball(str(tmp_path))


def test_parse_scope_errors():
    with pytest.raises(SystemExit):
        cli.parse_scope("frobs/x")
    assert cli.parse_scope("models/m") == ("Model", "m")
    assert cli.parse_scope("datasets") == ("Dataset", None)


def test_chat_streams_against_live_server(monkeypatch, capsys):
    """`rbt chat --url` drives the real SSE endpoint: deltas print as they
    arrive and the conversation accumulates for multi-turn context."""
    import asyncio
    import socket
    import threading

    import jax
    from aiohttp import web

    from runbooks_tpu.models.config import get_config
    from runbooks_tpu.models.transformer import init_params
    from runbooks_tpu.serve.api import create_server
    from runbooks_tpu.cli.main import main as cli_main

    cfg = get_config("debug", dtype="float32")
    app = create_server(cfg, init_params(cfg, jax.random.key(0)),
                        max_slots=2)
    started = threading.Event()
    bound = {}

    def run_app():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)  # OS-assigned: no TOCTOU
        loop.run_until_complete(site.start())
        bound["port"] = site._server.sockets[0].getsockname()[1]
        started.set()
        loop.run_forever()

    threading.Thread(target=run_app, daemon=True).start()
    assert started.wait(timeout=30)
    port = bound["port"]

    lines = iter(["hello there", "/quit"])
    monkeypatch.setattr("builtins.input",
                        lambda prompt="": next(lines))
    rc = cli_main(["chat", "--url", f"http://127.0.0.1:{port}",
                   "--max-tokens", "6", "--temperature", "0.0"])
    assert rc == 0
    out = capsys.readouterr().out
    # Something streamed back (byte tokenizer output is arbitrary text,
    # so assert non-empty reply rather than specific content).
    assert len(out.strip()) > 0


def test_notebook_resume_reattaches_without_upload(monkeypatch):
    """`rbt notebook --resume NAME`: unsuspends, waits for the controller
    to bring the pod back (suspended notebooks are NOT ready), then
    port-forwards — no manifests or upload involved (reference:
    sub notebook --resume)."""
    import runbooks_tpu.cli.main as cli

    client = FakeCluster()
    client.create({"apiVersion": API_VERSION, "kind": "Notebook",
                   "metadata": {"name": "nb1", "namespace": "default"},
                   "spec": {"image": "img", "suspend": True},
                   "status": {"ready": False}})
    monkeypatch.setattr(cli, "make_client", lambda args: client)
    forwarded = {}
    monkeypatch.setattr(
        cli, "_kubectl_port_forward",
        lambda target, local, remote, ns: forwarded.update(
            target=target, local=local) or 0)

    def controller():  # readiness only AFTER the unsuspend lands
        for _ in range(200):
            nb = client.get(API_VERSION, "Notebook", "default", "nb1")
            if nb["spec"].get("suspend") is False:
                nb.setdefault("status", {})["ready"] = True
                client.update_status(nb)
                return
            time.sleep(0.02)

    threading.Thread(target=controller, daemon=True).start()
    rc = cli.main(["notebook", "--resume", "nb1", "--no-sync",
                   "--timeout", "10"])
    assert rc == 0
    nb = client.get(API_VERSION, "Notebook", "default", "nb1")
    assert nb["spec"]["suspend"] is False  # unsuspended on resume
    assert forwarded["target"] == "pod/nb1-notebook"

    # Unknown name fails cleanly; --build conflicts loudly.
    with pytest.raises(SystemExit, match="not found"):
        cli.main(["notebook", "--resume", "ghost"])
    with pytest.raises(SystemExit, match="drop --build"):
        cli.main(["notebook", "--resume", "nb1", "--build", "."])
