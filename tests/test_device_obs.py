"""Device-level observability tests (ISSUE 7, obs/device.py).

Covers: the recompilation sentinel (fires on a post-warmup shape-busted
request, stays silent across a steady decode loop), live-array attribution
math, cost-analysis roofline classification on known matmuls, the CPU
degradation path (memory_stats() absent), GET /debug/memory and
/debug/programs, the /debug/profile memory-snapshot bundle, the fleet
mirror of the new xla_*/device_* families, and the `rbt top` HBM/SLOTS
columns.
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import init_params
from runbooks_tpu.obs import device as obs_device
from runbooks_tpu.obs import metrics as obs_metrics
from runbooks_tpu.obs.metrics import CATALOG, Registry


def tiny_cfg():
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32",
    )


@pytest.fixture(autouse=True)
def clean_sentinel_state():
    """Process-global steadiness must not leak between tests (or from a
    trainer/engine test that ran earlier in the session)."""
    obs_device.SENTINEL.clear_steady()
    yield
    obs_device.SENTINEL.clear_steady()


# ---------------------------------------------------------------------------
# Recompilation sentinel
# ---------------------------------------------------------------------------

def test_sentinel_counts_compiles_and_flags_post_steady():
    sentinel = obs_device.SENTINEL
    assert sentinel.install()  # idempotent; True = monitoring feed live
    reg = obs_metrics.REGISTRY
    t0, u0 = sentinel.total, sentinel.unexpected
    c0 = reg.counter_value("xla_compilations_total")

    f = jax.jit(lambda x: x * 2 + 1)
    # Inputs built up front: array creation itself compiles tiny
    # broadcast programs, which must not confound the counts below.
    x7, x9, x11 = jnp.ones(7), jnp.ones(9), jnp.ones(11)
    f(x7).block_until_ready()                   # fresh shape -> compile
    assert sentinel.total > t0
    assert reg.counter_value("xla_compilations_total") > c0
    assert sentinel.unexpected == u0            # nothing steady yet

    sentinel.mark_steady("test")
    try:
        f(x7).block_until_ready()               # cache hit: silent
        assert sentinel.unexpected == u0
        f(x9).block_until_ready()               # new shape: flagged
        assert sentinel.unexpected == u0 + 1
        assert sentinel.last_unexpected[-1]["steady"] == ["test"]
        # expected() masks intentional compiles on this thread.
        with sentinel.expected():
            f(x11).block_until_ready()
        assert sentinel.unexpected == u0 + 1
    finally:
        sentinel.clear_steady("test")


def test_sentinel_silent_across_steady_decode_loop(capsys):
    """Full warmup -> generate traffic across admissions and decode
    chunks -> zero unexpected compiles (the engine's compile discipline,
    measured)."""
    from runbooks_tpu.serve.engine import InferenceEngine, Request

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2, seed=0)
    engine.warmup()
    assert "serve" in obs_device.SENTINEL.steady_components()
    assert engine.warmup_census["compiles"] > 0
    assert engine.warmup_census["prefill_programs"] == \
        len(engine.prefill_buckets) * 2  # rows {1, max_slots}
    out = capsys.readouterr().out
    assert "warmup census" in out          # grep-able line kept
    assert "compiles in" in out            # ...now with compile seconds

    u0 = obs_device.SENTINEL.unexpected
    reqs = [Request(prompt_tokens=[1, 2, 3], max_tokens=4)
            for _ in range(3)]
    engine.generate(reqs)
    assert all(len(r.output_tokens) == 4 for r in reqs)
    assert obs_device.SENTINEL.unexpected == u0
    # Occupancy/prefix instrumentation advanced with the traffic.
    assert engine.prefix_lookups == 3 and engine.prefix_hits == 0


def test_sentinel_fires_on_shape_busted_request():
    """A warmed engine hit with a shape its warmup never compiled (a
    same-tick burst after a rows=(1,) warmup) stalls on a compile — the
    sentinel must make that loud."""
    from runbooks_tpu.serve.engine import InferenceEngine, Request

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2, seed=0)
    engine.warmup(rows=(1,))               # burst shape left cold
    reg = obs_metrics.REGISTRY
    u0 = obs_device.SENTINEL.unexpected
    c0 = reg.counter_value("xla_unexpected_compiles_total")
    reqs = [Request(prompt_tokens=[1, 2, 3], max_tokens=2)
            for _ in range(2)]
    engine.generate(reqs)                  # rows=2 prefill: cold compile
    assert obs_device.SENTINEL.unexpected == u0 + 1
    assert reg.counter_value("xla_unexpected_compiles_total") == c0 + 1
    assert obs_device.SENTINEL.last_unexpected[-1]["seconds"] > 0


def test_sentinel_unexpected_compile_emits_trace_instant(tmp_path,
                                                         monkeypatch):
    from runbooks_tpu.obs import trace as obs_trace

    monkeypatch.setenv("RBT_TRACE", "1")
    path = tmp_path / "trace.jsonl"
    obs_trace.configure(str(path))
    sentinel = obs_device.SENTINEL
    sentinel.install()
    sentinel.mark_steady("test")
    try:
        jax.jit(lambda x: x - 3)(jnp.ones(13)).block_until_ready()
    finally:
        sentinel.clear_steady("test")
        obs_trace.close()
        obs_trace.configure(None)
    events = [json.loads(ln.rstrip(",\n"))
              for ln in path.read_text().splitlines()[1:]]
    hits = [e for e in events if e["name"] == "unexpected_compile"]
    assert hits and hits[-1]["args"]["steady"] == "test"


def test_steady_claims_are_refcounted():
    """Two colocated engines both claim 'serve'; the first one stopping
    must not blind the sentinel for the survivor."""
    s = obs_device.SENTINEL
    s.mark_steady("serve")
    s.mark_steady("serve")
    s.clear_steady("serve")
    assert "serve" in s.steady_components()
    s.clear_steady("serve")
    assert "serve" not in s.steady_components()


def test_program_tracker_drops_dead_programs():
    """The tracker holds its jitted fns WEAKLY: a discarded engine's
    decode closures (which pin params + KV pool) must not survive via
    the census."""
    import gc

    tracker = obs_device.ProgramTracker()
    f = jax.jit(lambda x: x + 1)
    tracker.register("serve", "tmp", f)
    assert [e["name"] for e in tracker.census("serve")] == ["tmp"]
    del f
    gc.collect()
    assert tracker.census("serve") == []


def test_program_tracker_reregistration_resets_costs():
    """A rebuilt engine/run re-registers its entry points; the previous
    model's roofline costs must not survive into the new program's
    gauges (same shape sig, different model = silently wrong FLOPs)."""
    tracker = obs_device.ProgramTracker()
    tracker.register("serve", "prefill", None)
    tracker.record_cost("serve", "prefill", "b16r1", {"flops": 1.0})
    assert tracker.has_cost("serve", "prefill", "b16r1")
    tracker.register("serve", "prefill", None)   # engine rebuilt
    assert not tracker.has_cost("serve", "prefill", "b16r1")
    (entry,) = tracker.census("serve")
    assert entry["costs"] == {}


# ---------------------------------------------------------------------------
# Live-array attribution + CPU degradation
# ---------------------------------------------------------------------------

def test_live_array_census_attribution_math():
    weights = {"w": jnp.ones((32, 32), jnp.float32),     # 4096 B
               "b": jnp.ones((64,), jnp.float32)}        # 256 B
    cache = [jnp.zeros((16, 16), jnp.int8)]              # 256 B
    census = obs_device.live_array_census(
        {"weights": weights, "kv_cache": cache})
    cats = census["by_category"]
    assert cats["weights"] == 4096 + 256
    assert cats["kv_cache"] == 256
    # Categories + other sum EXACTLY to the total (acceptance: within
    # 5%; the construction makes it exact).
    assert sum(cats.values()) == census["total_bytes"]
    assert census["arrays"] >= 3
    # A group tree that shares no live arrays attributes zero.
    assert obs_device.live_array_census(
        {"ghost": {"x": np.ones(4)}})["by_category"]["ghost"] == 0


def test_device_memory_stats_cpu_degradation():
    """CPU has no memory_stats(): entries carry identity only, gauges
    stay unset, and memory_snapshot still answers via the census."""
    entries = obs_device.device_memory_stats()
    assert entries and entries[0]["platform"] == "cpu"
    assert "bytes_in_use" not in entries[0]
    reg = Registry()
    obs_device.set_memory_gauges(reg)
    assert "device_memory_bytes_in_use" not in reg.render()
    anchor = jnp.ones((8, 8))  # something live for the census to count
    snap = obs_device.memory_snapshot()
    assert snap["live_arrays"]["total_bytes"] >= anchor.nbytes


# ---------------------------------------------------------------------------
# Roofline
# ---------------------------------------------------------------------------

def test_roofline_classification_on_known_matmuls():
    # Square matmul: AI = 2n^3 / (3 * 4n^2) = n/6 flops/byte — far right
    # of a ridge of 10 at n=1024.
    n = 1024
    f = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((n, n), jnp.float32)
    cost = obs_device.cost_analysis_of(f, a, a)
    assert cost is not None
    assert cost["flops"] == pytest.approx(2 * n**3, rel=0.01)
    roof = obs_device.classify_roofline(cost["flops"], cost["hbm_bytes"],
                                        peak_flops=1e12,
                                        hbm_bytes_per_sec=100e9)
    assert roof["bound"] == "compute"
    assert roof["arithmetic_intensity"] > roof["ridge"] == 10.0

    # Matvec (decode-shaped): AI ~= 2 flops/byte — left of the ridge.
    g = jax.jit(lambda a, v: a @ v)
    v = jnp.ones((n,), jnp.float32)
    cost_v = obs_device.cost_analysis_of(g, a, v)
    roof_v = obs_device.classify_roofline(
        cost_v["flops"], cost_v["hbm_bytes"],
        peak_flops=1e12, hbm_bytes_per_sec=100e9)
    assert roof_v["bound"] == "bandwidth"
    assert roof_v["arithmetic_intensity"] < 10.0


def test_engine_decode_measures_bandwidth_bound():
    """The engine's 'decode is HBM-bound' analysis (serve/engine.py) is
    now a recorded cost: warmup captures per-program roofline costs and
    the decode program classifies bandwidth-bound."""
    from runbooks_tpu.serve.engine import InferenceEngine

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    engine = InferenceEngine(cfg, params, max_slots=2, seed=0)
    engine.warmup()
    census = {c["name"]: c for c in obs_device.PROGRAMS.census("serve")}
    decode = census[f"decode_v{engine.view_buckets[0]}"]
    assert decode["programs"] == 1
    (cost,) = decode["costs"].values()
    assert cost["bound"] == "bandwidth"
    assert cost["flops"] > 0 and cost["hbm_bytes"] > 0
    # Census gauges mirror into a registry.
    reg = Registry()
    obs_device.PROGRAMS.set_gauges(reg, component="serve")
    text = reg.render()
    assert 'xla_programs{component="serve"' in text
    assert "xla_program_bandwidth_bound" in text


# ---------------------------------------------------------------------------
# Serve HTTP endpoints
# ---------------------------------------------------------------------------

def test_http_debug_memory_endpoint():
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    app = create_server(cfg, params, max_slots=2)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 4, "temperature": 0.0})
            assert r.status == 200
            r = await client.get("/debug/memory")
            assert r.status == 200
            body = await r.json()
            cats = body["live_arrays"]["by_category"]
            total = body["live_arrays"]["total_bytes"]
            # Attribution sums to the census total (acceptance: 5%).
            assert sum(cats.values()) == total
            assert cats["weights"] > 0 and cats["kv_cache"] > 0
            assert body["kv_occupancy"]["slots_total"] == 2
            assert body["devices"][0]["platform"] == "cpu"

    import asyncio

    asyncio.run(drive())


def test_http_debug_programs_endpoint_and_metrics_families():
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    app = create_server(cfg, params, max_slots=2, warmup=True)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "hello", "max_tokens": 4, "temperature": 0.0})
            assert r.status == 200
            r = await client.get("/debug/programs")
            assert r.status == 200
            body = await r.json()
            assert body["compiles"]["total"] > 0
            assert "serve" in body["compiles"]["steady"]
            assert body["warmup_census"]["compiles"] > 0
            by_name = {p["name"]: p for p in body["programs"]}
            # The tracker is process-global: earlier tests' engines may
            # have registered other decode views — pick THIS engine's
            # (the one whose warmup recorded costs).
            decode = next(v for k, v in sorted(by_name.items())
                          if k.startswith("decode_v") and v["costs"])
            (cost,) = decode["costs"].values()
            # Traffic ran: analytic MFU joins the measured dispatch mean.
            assert cost["bound"] == "bandwidth"
            assert cost["measured_mean_seconds"] > 0
            assert cost["analytic_mfu"] > 0
            assert body["peaks"]["ridge_flops_per_byte"] > 0
            r = await client.get("/metrics")
            text = await r.text()
            for family in ("serve_slots_total", "serve_kv_cache_tokens",
                           "serve_kv_cache_capacity_tokens",
                           "serve_kv_occupancy_ratio",
                           "serve_prefix_lookups_total",
                           "serve_prefix_hits_total",
                           "xla_compilations_total",
                           "xla_unexpected_compiles_total",
                           "xla_programs", "xla_program_flops",
                           "xla_program_bandwidth_bound"):
                assert f"\n{family}" in text or \
                    text.startswith(family), family

    import asyncio

    asyncio.run(drive())


def test_debug_profile_bundles_memory_snapshot(tmp_path, monkeypatch):
    """A profile capture is self-contained: memory.json (devices + live
    census) lands beside the XLA trace."""
    from runbooks_tpu.obs import profile as obs_profile

    monkeypatch.setenv("RBT_CONTENT_DIR", str(tmp_path))
    log_dir = str(tmp_path / "cap")
    obs_profile.PROFILER.capture(log_dir, 0.05)
    snap_path = os.path.join(log_dir, "memory.json")
    assert os.path.exists(snap_path)
    snap = json.load(open(snap_path))
    assert snap["devices"][0]["platform"] == "cpu"
    assert snap["live_arrays"]["total_bytes"] >= 0


# ---------------------------------------------------------------------------
# Trainer integration
# ---------------------------------------------------------------------------

def test_trainer_device_obs_summary(tmp_path):
    from runbooks_tpu.parallel.mesh import MeshConfig
    from runbooks_tpu.train.optimizer import OptimizerConfig
    from runbooks_tpu.train.trainer import TrainJobConfig, run_training

    job = TrainJobConfig(
        model="debug", mesh=MeshConfig(), batch_size=4, seq_len=64,
        steps=4, checkpoint_every=4, log_every=2,
        artifacts_dir=str(tmp_path),
        optimizer=OptimizerConfig(total_steps=100, warmup_steps=0))
    summary = run_training(job)
    dev = summary["device_obs"]
    # The steady step loop ran clean; the roofline cost is attributed.
    assert dev["unexpected_compiles"] == 0
    assert dev["compiles"] >= 1
    assert dev["cost"]["flops"] > 0
    assert dev["cost"]["bound"] in ("compute", "bandwidth")
    # cost_analysis FLOPs and the 3x-forward formula must agree to ~2x —
    # they count different things (XLA fuses/elides) but catch either
    # being wildly wrong.
    ratio = dev["cost"]["flops"] / dev["formula_flops_per_step"]
    assert 0.3 < ratio < 3.0
    # Steadiness does not leak past the run.
    assert "train" not in obs_device.SENTINEL.steady_components()
    # metrics.json carries the same block.
    metrics = json.load(open(tmp_path / "metrics.json"))
    assert metrics["device_obs"]["cost"]["flops"] == dev["cost"]["flops"]


# ---------------------------------------------------------------------------
# Fleet mirror + rbt top columns
# ---------------------------------------------------------------------------

def _device_obs_replica_registry():
    reg = Registry()
    reg.set_gauge("serve_active_slots", 3)
    reg.set_gauge("serve_slots_total", 8)
    reg.set_gauge("serve_kv_occupancy_ratio", 0.25)
    reg.set_counter("serve_requests_total", 10)
    reg.set_counter("xla_compilations_total", 12)
    reg.set_counter("xla_unexpected_compiles_total", 1)
    reg.observe("xla_compile_seconds", 0.5)
    reg.set_gauge("xla_programs", 6, component="serve", program="prefill")
    reg.set_gauge("device_memory_bytes_in_use", 6e9, device="0")
    reg.set_gauge("device_memory_bytes_limit", 16e9, device="0")
    reg.set_gauge("device_memory_bytes_in_use", 3e9, device="1")
    reg.set_gauge("device_memory_bytes_limit", 16e9, device="1")
    return reg


def test_fleet_mirrors_device_obs_families():
    from runbooks_tpu.api.types import Server
    from runbooks_tpu.cloud.base import CommonConfig
    from runbooks_tpu.cloud.local import LocalCloud
    from runbooks_tpu.controller import fleet as fl
    from runbooks_tpu.controller.manager import Ctx
    from runbooks_tpu.k8s.fake import FakeCluster
    from runbooks_tpu.obs.metrics import serve_metrics
    from runbooks_tpu.sci.base import FakeSCI

    client = FakeCluster()
    ctx = Ctx(client=client, cloud=LocalCloud(CommonConfig(
        cluster_name="t", artifact_bucket_url="file:///tmp/b",
        registry_url="r:5000")), sci=FakeSCI())
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    reg_replica = _device_obs_replica_registry()
    httpd = serve_metrics(0, reg_replica)
    client.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "srv-a", "namespace": "default",
                     "labels": {"server": "srv", "role": "run"},
                     "annotations": {fl.METRICS_PORT_ANNOTATION:
                                     str(httpd.server_address[1])}},
        "spec": {"containers": [{"name": "c"}]},
        "status": {"phase": "Running", "podIP": "127.0.0.1"},
    })
    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry)
    try:
        assert scraper.scrape_once() == 1
    finally:
        httpd.shutdown()
        httpd.server_close()
    text = registry.render()
    # xla_* and device_* mirror per replica like serve_*/train_*.
    assert ('xla_unexpected_compiles_total{kind="Server",name="srv",'
            'namespace="default",replica="srv-a"} 1.0') in text
    assert ('device_memory_bytes_in_use{device="0",kind="Server",'
            'name="srv",namespace="default",replica="srv-a"}') in text
    assert 'xla_compile_seconds_bucket' in text
    # And everything mirrored is cataloged (docs drift check covers docs).
    families = obs_metrics.parse_exposition(text)
    runtime = {n for n in families
               if n.startswith(("serve_", "train_", "xla_", "device_"))}
    assert runtime <= set(CATALOG), runtime - set(CATALOG)


def test_rbt_top_hbm_and_slot_columns(capsys):
    """`rbt top` renders HBM% (summed across a replica's devices) and
    slot-utilization columns from the fleet exposition."""
    from runbooks_tpu.cli.main import _top_rows_from_metrics

    reg = _device_obs_replica_registry()
    labels = {"kind": "Server", "namespace": "default", "name": "srv",
              "replica": "srv-a"}
    fleet = Registry()
    fleet.set_gauge("fleet_scrape_up", 1, **labels)
    fleet.set_gauge("fleet_scrape_age_seconds", 0.0, **labels)
    for fam in ("serve_active_slots", "serve_slots_total",
                "serve_kv_occupancy_ratio"):
        fleet.set_gauge(fam, {"serve_active_slots": 3,
                              "serve_slots_total": 8,
                              "serve_kv_occupancy_ratio": 0.25}[fam],
                        **labels)
    fleet.set_gauge("device_memory_bytes_in_use", 6e9, device="0",
                    **labels)
    fleet.set_gauge("device_memory_bytes_limit", 16e9, device="0",
                    **labels)
    fleet.set_gauge("device_memory_bytes_in_use", 3e9, device="1",
                    **labels)
    fleet.set_gauge("device_memory_bytes_limit", 16e9, device="1",
                    **labels)
    header, rows = _top_rows_from_metrics(fleet.render())
    assert header[5] == "HBM" and header[6] == "SLOTS"
    (row,) = rows
    assert row[0] == "servers/srv"
    assert row[5] == "28%"           # (6+3)/(16+16) GB
    assert row[6] == "3/8 kv=25%"
    # A CPU replica (no device_memory_* series) degrades to '-'.
    bare = Registry()
    bare.set_gauge("fleet_scrape_up", 1, **labels)
    bare.set_gauge("serve_active_slots", 1, **labels)
    _, rows = _top_rows_from_metrics(bare.render())
    assert rows[0][5] == "-" and rows[0][6] == "-"


def test_catalog_covers_device_obs_families():
    """Every family obs/device.py + the engine/api emit is cataloged, so
    the PR-6 docs drift check extends to the device plane."""
    for name in ("xla_compilations_total", "xla_unexpected_compiles_total",
                 "xla_compile_seconds", "xla_programs",
                 "xla_program_flops", "xla_program_hbm_bytes",
                 "xla_program_arithmetic_intensity",
                 "xla_program_bandwidth_bound",
                 "device_memory_bytes_in_use", "device_memory_peak_bytes",
                 "device_memory_bytes_limit",
                 "device_memory_headroom_bytes",
                 "serve_slots_total", "serve_kv_cache_tokens",
                 "serve_kv_cache_capacity_tokens",
                 "serve_kv_occupancy_ratio", "serve_prefix_lookups_total",
                 "serve_prefix_hits_total", "train_analytic_mfu"):
        assert name in CATALOG, name


# ---------------------------------------------------------------------------
# Bench axis
# ---------------------------------------------------------------------------

def test_bench_device_obs_axis(monkeypatch, capsys):
    """RBT_BENCH_DEVICE_OBS=1 runs the steady-loop compile gate and
    reports analytic vs formula MFU side by side."""
    import bench

    monkeypatch.setenv("RBT_BENCH_DEVICE_OBS", "1")
    monkeypatch.setenv("RBT_BENCH_BS", "2")
    monkeypatch.setenv("RBT_BENCH_SEQ", "64")
    bench.inner()
    line = [ln for ln in capsys.readouterr().out.splitlines()
            if ln.startswith("{")][-1]
    out = json.loads(line)
    assert out["value"] == 0                 # zero unexpected compiles
    assert out["vs_baseline"] == 1.0
    assert out["mfu_analytic"] > 0 and out["mfu_formula"] > 0
    assert 0.3 < out["flops_ratio"] < 3.0
    assert out["bound"] in ("compute", "bandwidth")
