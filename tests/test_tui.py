"""Headless tests for the TUI update loops.

The reference's bubbletea models are pure state machines testable without a
terminal; ours keep that property. Tests drive update(msg) directly, run
returned commands synchronously with a collecting `send`, and assert on
ANSI-stripped view() text. (Reference test strategy analog: SURVEY.md §4 —
the TUI layer has no terminal in CI either.)
"""

from __future__ import annotations

import queue

from runbooks_tpu.api.types import API_VERSION
from runbooks_tpu.k8s.fake import FakeCluster
from runbooks_tpu.tui import messages as m
from runbooks_tpu.tui.core import decode_keys
from runbooks_tpu.tui.flows import (
    ApplyFlow,
    DeleteFlow,
    GetFlow,
    NotebookFlow,
    RunFlow,
    ServeFlow,
)
from runbooks_tpu.tui.submodels import (
    PodsModel,
    ReadinessModel,
    UploadModel,
)
from runbooks_tpu.tui.widgets import Viewport, render_table, strip_ansi


def run_cmds(model, cmds, collected=None, depth=0):
    """Run commands synchronously, feeding resulting messages back into the
    model (a deterministic stand-in for Program's thread pump)."""
    collected = collected if collected is not None else []
    assert depth < 12, "runaway command loop"
    for cmd in cmds or []:
        if getattr(cmd, "long_running", False):
            continue  # watches/polls: Program runs these on threads
        inbox: "queue.Queue[object]" = queue.Queue()
        result = cmd(inbox.put)
        msgs = []
        while not inbox.empty():
            msgs.append(inbox.get())
        if result is not None:
            msgs.append(result)
        for msg in msgs:
            collected.append(msg)
            follow = model.update(msg)
            run_cmds(model, follow, collected, depth + 1)
    return collected


def feed(model, msg):
    """update() one message, then run any returned commands synchronously."""
    cmds = model.update(msg)
    return run_cmds(model, cmds)


def notebook_obj(name="nb1", ready=False, conditions=None):
    obj = {"apiVersion": API_VERSION, "kind": "Notebook",
           "metadata": {"name": name, "namespace": "default"},
           "spec": {"image": "img"}}
    if conditions is not None or ready:
        obj["status"] = {"ready": ready, "conditions": conditions or []}
    return obj


# ---------------------------------------------------------------------------
# Widgets
# ---------------------------------------------------------------------------

def test_viewport_tails_and_normalizes_cr():
    vp = Viewport(height=3, width=40)
    vp.append("progress 10%\rprogress 50%\rdone")
    for i in range(10):
        vp.append(f"line {i}")
    text = strip_ansi(vp.view())
    assert "line 9" in text and "line 7" in text
    assert "line 2" not in text  # beyond tail window
    assert len(text.split("\n")) == 3


def test_render_table_aligns_with_ansi():
    from runbooks_tpu.tui.widgets import green
    out = strip_ansi(render_table(
        ["NAME", "READY"], [["models/m1", green("yes")], ["servers/s1", "no"]]))
    lines = out.split("\n")
    assert lines[0].index("READY") == lines[1].index("yes")
    assert lines[0].index("READY") == lines[2].index("no")


def test_decode_keys():
    assert decode_keys(b"q") == ["q"]
    assert decode_keys(b"\x03") == ["ctrl+c"]
    assert decode_keys(b"\x1b[A") == ["up"]
    assert decode_keys(b"\x1b") == ["esc"]
    assert decode_keys(b"\r") == ["enter"]
    assert decode_keys(b"ab") == ["a", "b"]


# ---------------------------------------------------------------------------
# Sub-models
# ---------------------------------------------------------------------------

def test_readiness_checklist_renders_conditions():
    rm = ReadinessModel(notebook_obj(conditions=[
        {"type": "Built", "status": "True"},
        {"type": "Complete", "status": "False", "reason": "JobNotComplete"},
    ]))
    view = strip_ansi(rm.view())
    assert "✔ Built" in view
    assert "✗ Complete (JobNotComplete)" in view

    rm.update(m.ObjectReady(notebook_obj(ready=True)))
    assert "Ready" in strip_ansi(rm.view())


def test_upload_model_shows_latest_progress():
    um = UploadModel("nb1")
    um.update(m.UploadProgress("nb1", "packed 123 bytes"))
    assert "packed 123 bytes" in strip_ansi(um.view())
    um.update(m.TarballUploaded(notebook_obj()))
    assert "✔" in strip_ansi(um.view())


def test_pods_model_streams_logs_for_running_pods():
    fake = FakeCluster()
    fake.set_pod_logs("default", "nb1-notebook", "hello\nworld")
    pm = PodsModel(fake)
    pod = {"apiVersion": "v1", "kind": "Pod",
           "metadata": {"name": "nb1-notebook", "namespace": "default",
                        "labels": {"notebook": "nb1", "role": "run"}},
           "status": {"phase": "Running"}}
    cmds = pm.update(m.PodWatch("ADDED", pod))
    assert cmds, "a running pod should start a log stream"
    msgs = run_cmds(pm, cmds)
    assert any(isinstance(x, m.PodLogs) for x in msgs)
    view = strip_ansi(pm.view())
    assert "Run nb1-notebook (Running)" in view
    assert "world" in view

    # Same pod again: no duplicate stream.
    assert not pm.update(m.PodWatch("MODIFIED", pod))
    pm.update(m.PodWatch("DELETED", pod))
    assert "nb1-notebook" not in strip_ansi(pm.view())


# ---------------------------------------------------------------------------
# Flows
# ---------------------------------------------------------------------------

def manifests_dir(tmp_path, docs):
    import yaml
    f = tmp_path / "app.yaml"
    f.write_text(yaml.safe_dump_all(docs))
    return str(tmp_path)


def test_notebook_flow_applies_and_reaches_ready(tmp_path):
    fake = FakeCluster()
    flow = NotebookFlow(fake, manifests_dir(tmp_path, [notebook_obj()]),
                        "default", sync=False,
                        pf_runner=lambda argv: 0)

    # Manifest discovery -> apply (no upload spec) — run only the manifest
    # load; wait_ready would block until the controller acts.
    msgs = run_cmds(flow, flow.init()[:1])
    assert any(isinstance(x, m.ManifestsLoaded) for x in msgs)
    assert fake.get(API_VERSION, "Notebook", "default", "nb1") is not None
    assert flow.notebook is not None

    # Controller-side readiness, delivered as messages.
    flow.update(m.ObjectUpdate(notebook_obj(conditions=[
        {"type": "Built", "status": "False", "reason": "Building"}])))
    assert "✗ Built" in strip_ansi(flow.view())

    cmds = flow.update(m.ObjectReady(notebook_obj(ready=True)))
    # Port-forward command fires (runner stub returns success).
    msgs = run_cmds(flow, cmds)
    assert any(isinstance(x, m.PortForwardReady) for x in msgs)
    assert "http://localhost:8888" in strip_ansi(flow.view())


def test_notebook_flow_quit_confirm_suspend(tmp_path):
    fake = FakeCluster()
    fake.create(notebook_obj())
    flow = NotebookFlow(fake, manifests_dir(tmp_path, [notebook_obj()]),
                        "default", sync=False)
    flow.notebook = notebook_obj()

    assert flow.update(m.Key("q")) == []
    assert flow.quitting
    assert 'suspend' in strip_ansi(flow.view())

    # esc cancels.
    flow.update(m.Key("esc"))
    assert not flow.quitting

    # q then s suspends via SSA patch and quits with a goodbye.
    flow.update(m.Key("q"))
    msgs = feed(flow, m.Key("s"))
    assert any(isinstance(x, m.Quit) for x in msgs)
    assert flow.goodbye == "Notebook suspended."
    nb = fake.get(API_VERSION, "Notebook", "default", "nb1")
    assert nb["spec"]["suspend"] is True


def test_notebook_flow_delete_key(tmp_path):
    fake = FakeCluster()
    fake.create(notebook_obj())
    flow = NotebookFlow(fake, manifests_dir(tmp_path, [notebook_obj()]),
                        "default", sync=False)
    flow.notebook = notebook_obj()
    flow.update(m.Key("q"))
    msgs = feed(flow, m.Key("d"))
    assert any(isinstance(x, m.Quit) for x in msgs)
    assert fake.get(API_VERSION, "Notebook", "default", "nb1") is None


def test_run_flow_increments_name_and_quits_on_ready(tmp_path):
    fake = FakeCluster()
    fake.create(notebook_obj("job"))       # existing base name
    fake.create(notebook_obj("job-3"))     # existing increment
    flow = RunFlow(fake, manifests_dir(tmp_path, [notebook_obj("job")]),
                   "default", increment=True)
    run_cmds(flow, flow.init()[:1])
    assert flow.obj["metadata"]["name"] == "job-4"
    assert fake.get(API_VERSION, "Notebook", "default", "job-4") is not None

    msgs = feed(flow, m.ObjectReady(notebook_obj("job-4", ready=True)))
    assert any(isinstance(x, m.Quit) for x in msgs)
    assert "ready" in flow.goodbye


def test_serve_flow_port_forwards_when_ready():
    fake = FakeCluster()
    server = {"apiVersion": API_VERSION, "kind": "Server",
              "metadata": {"name": "srv", "namespace": "default"},
              "spec": {"model": {"name": "m1"}}}
    fake.create(server)
    flow = ServeFlow(fake, "srv", "default", local_port=8001,
                     pf_runner=lambda argv: 0)
    run_cmds(flow, flow.init()[:1])
    assert flow.server is not None

    server["status"] = {"ready": True}
    msgs = feed(flow, m.ObjectReady(server))
    assert any(isinstance(x, m.PortForwardReady) for x in msgs)
    assert "http://localhost:8001" in strip_ansi(flow.view())


def test_serve_flow_missing_server_errors():
    flow = ServeFlow(FakeCluster(), "absent", "default")
    msgs = run_cmds(flow, flow.init())
    assert any(isinstance(x, m.Error) for x in msgs)
    assert flow.final_error is not None
    assert "not found" in str(flow.final_error)


def test_apply_flow_applies_all_and_quits(tmp_path):
    fake = FakeCluster()
    docs = [notebook_obj("a"),
            {"apiVersion": API_VERSION, "kind": "Model",
             "metadata": {"name": "mm", "namespace": "default"},
             "spec": {"image": "img"}}]
    flow = ApplyFlow(fake, manifests_dir(tmp_path, docs), "default",
                     wait=False)
    msgs = run_cmds(flow, flow.init())
    assert any(isinstance(x, m.Quit) for x in msgs)
    assert fake.get(API_VERSION, "Notebook", "default", "a") is not None
    assert fake.get(API_VERSION, "Model", "default", "mm") is not None
    assert "applied" in flow.goodbye


def test_delete_flow_marks_absent_and_deleted():
    fake = FakeCluster()
    fake.create(notebook_obj("nb1"))
    flow = DeleteFlow(fake, [("Notebook", "nb1"), ("Model", "ghost")],
                      "default")
    msgs = run_cmds(flow, flow.init())
    assert any(isinstance(x, m.Quit) for x in msgs)
    view = strip_ansi(flow.view())
    assert "✔ notebooks/nb1" in view
    assert "absent models/ghost" in view
    assert fake.get(API_VERSION, "Notebook", "default", "nb1") is None


def test_get_flow_tracks_watch_events():
    flow = GetFlow(FakeCluster(), "default")
    flow.update(m.WatchEvent("ADDED", notebook_obj("nb1")))
    flow.update(m.WatchEvent("ADDED", notebook_obj("nb2", ready=True)))
    view = strip_ansi(flow.view())
    assert "notebooks/nb1" in view and "notebooks/nb2" in view
    assert "Total: 2" in view

    flow.update(m.WatchEvent("DELETED", notebook_obj("nb1")))
    view = strip_ansi(flow.view())
    assert "notebooks/nb1" not in view
    assert "Total: 1" in view


def test_get_flow_name_filter():
    flow = GetFlow(FakeCluster(), "default", kind_filter="Notebook",
                   name_filter="nb2")
    flow.update(m.WatchEvent("ADDED", notebook_obj("nb1")))
    flow.update(m.WatchEvent("ADDED", notebook_obj("nb2")))
    view = strip_ansi(flow.view())
    assert "nb1" not in view and "nb2" in view


def test_get_flow_quits_on_q():
    flow = GetFlow(FakeCluster(), "default")
    msgs = feed(flow, m.Key("q"))
    assert any(isinstance(x, m.Quit) for x in msgs)


def test_flow_error_message_renders():
    flow = GetFlow(FakeCluster(), "default")
    feed(flow, m.Error(RuntimeError("boom")))
    assert "Error: boom" in strip_ansi(flow.view())


def test_notebook_flow_resume_skips_upload():
    """resume mode: init fetches + unsuspends the existing notebook and
    goes straight to readiness (no manifests, no upload)."""
    fake = FakeCluster()
    nb = notebook_obj()
    nb["spec"]["suspend"] = True
    fake.create(nb)
    flow = NotebookFlow(fake, ".", "default", sync=False, resume="nb1",
                        pf_runner=lambda argv: 0)
    msgs = run_cmds(flow, flow.init())
    assert any(isinstance(x, m.Applied) for x in msgs)
    assert not any(isinstance(x, m.ManifestsLoaded) for x in msgs)
    cur = fake.get(API_VERSION, "Notebook", "default", "nb1")
    assert cur["spec"]["suspend"] is False
    assert flow.notebook is not None

    # Missing notebook surfaces an error.
    flow2 = NotebookFlow(fake, ".", "default", resume="ghost")
    msgs = run_cmds(flow2, flow2.init())
    assert any(isinstance(x, m.Error) for x in msgs)
