"""Flight recorder + distributed request tracing + incident snapshots
(ISSUE 12).

Covers: the always-on bounded span ring (recording with RBT_TRACE=0,
request-id indexing, boundedness under sustained traffic), tail
sampling (slow/deadline requests promoted to trace.jsonl, fast ones
not), Perfetto multi-pod metadata (process_name/thread_name events,
host-derived trace pid), gateway hop stitching end to end through the
real HTTP stack (minted X-Request-Id, forwarded traceparent, gateway
access log, `rbt trace` merging gateway + 2 replicas into one
clock-ordered timeline), and incident snapshots (fault-injected engine
crash and SLOViolated onset each produce exactly one parseable bundle,
debounce verified; trainer max_bad_steps abort; /debug/incident(s)
endpoints; `rbt incidents`).
"""

import asyncio
import dataclasses
import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from runbooks_tpu.obs import flight as obs_flight
from runbooks_tpu.obs import incident as obs_incident
from runbooks_tpu.obs import trace as obs_trace

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


@pytest.fixture(autouse=True)
def clean_obs_state(monkeypatch):
    """Flight ring + incident debounce book are process-global: every
    test starts from a clean slate and leaves one behind."""
    obs_flight.RING.clear()
    obs_incident.MANAGER.reset()
    monkeypatch.delenv("RBT_TRACE", raising=False)
    monkeypatch.delenv("RBT_TRACE_TAIL_MS", raising=False)
    monkeypatch.delenv("RBT_FLIGHT", raising=False)
    yield
    obs_trace.close()
    obs_trace.configure(None)
    obs_flight.RING.clear()
    obs_incident.MANAGER.reset()


def tiny_cfg():
    from runbooks_tpu.models.config import get_config

    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32")


def tiny_params(cfg):
    import jax

    from runbooks_tpu.models.transformer import init_params

    return jax.jit(lambda r: init_params(cfg, r))(jax.random.key(0))


# ---------------------------------------------------------------------------
# Ring mechanics
# ---------------------------------------------------------------------------

def test_ring_bounded_and_indexed_by_request_id():
    ring = obs_flight.FlightRecorder(capacity=32)
    for i in range(100):
        ring.record({"name": "decode", "ph": "X", "ts": float(i),
                     "args": {"request_ids": [f"r-{i % 4}"]}})
    stats = ring.stats()
    assert stats["events"] == 32 and stats["capacity"] == 32
    assert stats["recorded"] == 100 and stats["dropped"] == 68
    # Request-id filter matches both the list form and the /i suffix.
    assert all("r-1" in e["args"]["request_ids"]
               for e in ring.snapshot(request_id="r-1"))
    ring.record({"name": "prefill", "ph": "X", "ts": 1e9,
                 "args": {"request_id": "r-9/0"}})
    assert len(ring.snapshot(request_id="r-9")) == 1


def test_spans_record_into_ring_without_rbt_trace(tmp_path):
    obs_trace.configure(str(tmp_path / "trace.jsonl"))
    with obs_trace.span("prefill", bucket=16, request_ids=["rid-a"]):
        pass
    obs_trace.instant("tick", request_id="rid-a")
    # Ring has both; the FILE has neither (RBT_TRACE off).
    events = obs_flight.RING.snapshot(request_id="rid-a")
    assert {e["name"] for e in events} == {"prefill", "tick"}
    assert not os.path.exists(tmp_path / "trace.jsonl")
    # RBT_FLIGHT=0 restores the zero-cost null path.
    os.environ["RBT_FLIGHT"] = "0"
    try:
        assert obs_trace.span("x") is obs_trace.span("y")
    finally:
        del os.environ["RBT_FLIGHT"]


def test_trace_file_carries_perfetto_metadata(tmp_path, monkeypatch):
    """Multi-pod merge fix: each file generation opens with
    process_name/thread_name metadata naming component@host + the real
    pid, and events carry the host-derived trace pid."""
    monkeypatch.setenv("RBT_TRACE", "1")
    path = str(tmp_path / "trace.jsonl")
    obs_trace.configure(path)
    obs_flight.set_component("serve")
    try:
        with obs_trace.span("phase", i=0):
            pass

        def other():
            with obs_trace.span("phase", i=1):
                pass

        t = threading.Thread(target=other)
        t.start()
        t.join()
    finally:
        obs_trace.close()
        obs_trace.configure(None)
        obs_flight.set_component("proc")
    events = []
    with open(path) as f:
        assert f.readline().strip() == "["
        for line in f:
            line = line.strip().rstrip(",")
            if line:
                events.append(json.loads(line))
    meta = [e for e in events if e["ph"] == "M"]
    procs = [e for e in meta if e["name"] == "process_name"]
    threads = [e for e in meta if e["name"] == "thread_name"]
    assert len(procs) == 1
    assert "serve@" in procs[0]["args"]["name"]
    assert f"pid={os.getpid()}" in procs[0]["args"]["name"]
    assert len(threads) == 2  # two distinct recording threads
    # Events carry the derived trace pid (stable, host-scoped), and the
    # metadata rows carry the same one — merged files can't collide.
    spans = [e for e in events if e["ph"] == "X"]
    assert {e["pid"] for e in spans} == {obs_trace.trace_pid()}
    assert procs[0]["pid"] == obs_trace.trace_pid()


# ---------------------------------------------------------------------------
# Engine: always-on timelines + tail sampling
# ---------------------------------------------------------------------------

def _engine(cfg, params, **kw):
    from runbooks_tpu.serve.engine import InferenceEngine

    return InferenceEngine(cfg, params, max_slots=2, seed=0, **kw)


def test_engine_timeline_reconstructible_from_ring(tmp_path):
    """RBT_TRACE stays OFF: the ring alone reconstructs one request's
    queue-wait -> prefill -> decode path, and stays bounded under
    sustained traffic."""
    from runbooks_tpu.serve.engine import Request

    obs_trace.configure(str(tmp_path / "trace.jsonl"))
    # Small ring so 8 waves genuinely wrap it; the LAST wave's full
    # timeline must still be reconstructible from what remains.
    obs_flight.RING.resize(32)
    try:
        cfg = tiny_cfg()
        engine = _engine(cfg, tiny_params(cfg))
        for wave in range(8):
            reqs = [Request(prompt_tokens=[1, 2, 3], max_tokens=4,
                            request_id=f"w{wave}-r{i}")
                    for i in range(2)]
            engine.generate(reqs)
        events = obs_flight.RING.snapshot(request_id="w7-r0")
        names = {e["name"] for e in events}
        assert {"queue_wait", "prefill", "decode"} <= names
        stats = obs_flight.RING.stats()
        assert stats["events"] <= stats["capacity"] == 32
        assert stats["dropped"] > 0  # sustained traffic really wrapped
        assert not os.path.exists(tmp_path / "trace.jsonl")
    finally:
        obs_flight.RING.resize(obs_flight.ring_capacity())


def test_tail_sampling_promotes_only_interesting_requests(
        tmp_path, monkeypatch):
    from runbooks_tpu.obs.metrics import REGISTRY
    from runbooks_tpu.serve.engine import Request

    path = str(tmp_path / "trace.jsonl")
    obs_trace.configure(path)
    cfg = tiny_cfg()
    engine = _engine(cfg, tiny_params(cfg))

    # Threshold far above any CPU request time: nothing promotes.
    monkeypatch.setenv("RBT_TRACE_TAIL_MS", "3600000")
    engine.generate([Request(prompt_tokens=[1, 2, 3], max_tokens=4,
                             request_id="fast-1")])
    assert not os.path.exists(path)

    # Threshold 0: every finish is "slow" -> promoted even with
    # RBT_TRACE=0, with the tail_sample marker naming the reason.
    monkeypatch.setenv("RBT_TRACE_TAIL_MS", "0")
    before = REGISTRY.counter_value("serve_tail_samples_total",
                                    reason="slow")
    engine.generate([Request(prompt_tokens=[1, 2, 3], max_tokens=4,
                             request_id="slow-1")])
    assert REGISTRY.counter_value("serve_tail_samples_total",
                                  reason="slow") == before + 1
    events = []
    with open(path) as f:
        assert f.readline().strip() == "["
        for line in f:
            line = line.strip().rstrip(",")
            if line:
                events.append(json.loads(line))
    promoted = [e for e in events
                if obs_flight._matches(e, "slow-1")]
    assert {"queue_wait", "prefill", "decode"} <= \
        {e["name"] for e in promoted}
    markers = [e for e in events if e["name"] == "tail_sample"]
    assert markers and markers[-1]["args"]["reason"] == "slow"
    # The fast request's timeline never reached the file.
    assert not any(obs_flight._matches(e, "fast-1") for e in events)

    # Deadline expiry promotes regardless of the latency threshold.
    monkeypatch.delenv("RBT_TRACE_TAIL_MS")
    before_dl = REGISTRY.counter_value("serve_tail_samples_total",
                                       reason="deadline")
    req = Request(prompt_tokens=[1, 2, 3], max_tokens=512,
                  deadline_s=0.001, request_id="late-1")
    engine.generate([req])
    assert req.finish_reason == "deadline"
    assert REGISTRY.counter_value("serve_tail_samples_total",
                                  reason="deadline") == before_dl + 1


# ---------------------------------------------------------------------------
# Incident snapshots: engine crash, trainer abort, HTTP surface
# ---------------------------------------------------------------------------

def _bundles(root):
    inc_dir = os.path.join(str(root), "artifacts", "incidents")
    if not os.path.isdir(inc_dir):
        return []
    return sorted(os.path.join(inc_dir, n) for n in os.listdir(inc_dir)
                  if n.endswith(".json"))


def test_engine_crash_captures_exactly_one_bundle(tmp_path, monkeypatch):
    """RBT_FAULT_INJECT=engine:K: the worker's crash handler dooms the
    in-flight futures, captures ONE incident bundle (debounce verified),
    error-promotes the doomed timelines, and the reset engine serves
    again."""
    from runbooks_tpu.serve.api import EngineWorker
    from runbooks_tpu.serve.engine import EngineStepFailed, Request

    monkeypatch.setenv("RBT_CONTENT_DIR", str(tmp_path))
    obs_trace.configure(str(tmp_path / "artifacts" / "trace.jsonl"))
    # Fault at step 1: step 0 completes (queue_wait/prefill/decode land
    # in the ring), then the second step blows up with the request
    # still in flight — the realistic mid-request crash.
    monkeypatch.setenv("RBT_FAULT_INJECT", "engine:1")
    cfg = tiny_cfg()
    engine = _engine(cfg, tiny_params(cfg))
    monkeypatch.delenv("RBT_FAULT_INJECT")
    worker = EngineWorker(engine)
    try:
        fut = worker.submit(Request(prompt_tokens=[1, 2, 3], max_tokens=32,
                                    request_id="doomed-1"))
        with pytest.raises(EngineStepFailed):
            fut.result(timeout=60)
        deadline = time.monotonic() + 10
        while not _bundles(tmp_path) and time.monotonic() < deadline:
            time.sleep(0.02)
        bundles = _bundles(tmp_path)
        assert len(bundles) == 1, bundles
        bundle = json.load(open(bundles[0]))
        assert bundle["reason"] == "engine_crash"
        assert "doomed-1" in bundle["extra"]["doomed_requests"]
        # The acceptance surface: flight ring + memory/program census +
        # metrics snapshot all present and parseable.
        assert bundle["flight"]["events"], "flight ring missing"
        assert "live_arrays" in bundle["memory"]
        assert any(p.get("component") == "serve"
                   for p in bundle["programs"])
        assert "serve_incidents_total" in bundle["metrics"]
        assert "unexpected" in bundle["compiles"]
        # Debounce: an immediate second capture for the same reason is
        # swallowed — a crash storm leaves one bundle per window.
        assert obs_incident.capture("engine_crash") is None
        assert len(_bundles(tmp_path)) == 1
        # Doomed request's timeline was error-promoted to trace.jsonl.
        trace_path = tmp_path / "artifacts" / "trace.jsonl"
        assert trace_path.exists()
        text = trace_path.read_text()
        assert "doomed-1" in text and "tail_sample" in text
        # The reset engine serves the next request normally.
        ok = worker.submit(Request(prompt_tokens=[1, 2, 3], max_tokens=4,
                                   request_id="after-1"))
        assert len(ok.result(timeout=60).output_tokens) == 4
    finally:
        worker.stop()


def test_trainer_max_bad_steps_abort_captures_incident(
        tmp_path, monkeypatch):
    from runbooks_tpu.parallel.mesh import MeshConfig
    from runbooks_tpu.train.optimizer import OptimizerConfig
    from runbooks_tpu.train.trainer import TrainJobConfig, run_training

    monkeypatch.setenv("RBT_FAULT_INJECT", "nonfinite:2+")
    job = TrainJobConfig(
        model="debug", model_overrides={"dtype": "float32"},
        mesh=MeshConfig(data=2, fsdp=2, tensor=2),
        optimizer=OptimizerConfig(learning_rate=1e-3, warmup_steps=0,
                                  total_steps=100, schedule="constant"),
        batch_size=4, seq_len=32, steps=10, checkpoint_every=100,
        log_every=1, max_bad_steps=2, artifacts_dir=str(tmp_path))
    with pytest.raises(RuntimeError, match="consecutive non-finite"):
        run_training(job)
    inc_dir = tmp_path / "incidents"
    bundles = sorted(inc_dir.glob("*.json"))
    assert len(bundles) == 1
    bundle = json.load(open(bundles[0]))
    assert bundle["reason"] == "train_max_bad_steps"
    assert bundle["extra"]["bad_streak"] == 2
    assert bundle["flight"]["events"], "trainer spans missing from ring"


def test_http_incident_endpoints_and_debounce(tmp_path, monkeypatch):
    """POST /debug/incident captures (once per debounce window); GET
    /debug/incidents lists and fetches."""
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    monkeypatch.setenv("RBT_CONTENT_DIR", str(tmp_path))
    cfg = tiny_cfg()
    app = create_server(cfg, tiny_params(cfg), max_slots=2)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "hi", "max_tokens": 2})
            assert r.status == 200
            r = await client.post("/debug/incident",
                                  json={"reason": "manual-test"})
            body = await r.json()
            assert body["path"] and not body["debounced"]
            assert os.path.exists(body["path"])
            # Same reason inside the window: debounced, still 1 bundle.
            r = await client.post("/debug/incident",
                                  json={"reason": "manual-test"})
            body2 = await r.json()
            assert body2["debounced"] and body2["path"] is None
            assert len(_bundles(tmp_path)) == 1
            r = await client.get("/debug/incidents")
            listing = await r.json()
            assert len(listing["incidents"]) == 1
            name = listing["incidents"][0]["name"]
            assert listing["incidents"][0]["reason"] == "manual-test"
            r = await client.get(f"/debug/incidents?name={name}")
            bundle = await r.json()
            assert bundle["reason"] == "manual-test"
            assert bundle["flight"]["events"]
            r = await client.get("/debug/incidents?name=../../etc/passwd")
            assert r.status == 404
            # /debug/flight on the serve tier: request-indexed.
            r = await client.get("/debug/flight")
            flight_body = await r.json()
            assert flight_body["component"] == "serve"
            assert flight_body["stats"]["events"] > 0
            # /metrics carries the new families.
            r = await client.get("/metrics")
            text = await r.text()
            assert "flight_ring_events" in text
            assert 'serve_incidents_total{reason="manual-test"} 1' in text
            assert "serve_incident_age_seconds" in text

    asyncio.run(drive())


# ---------------------------------------------------------------------------
# Gateway hop stitching + `rbt trace` end to end (real HTTP stack)
# ---------------------------------------------------------------------------

class _AppHost:
    """Run aiohttp apps on a dedicated thread's event loop so the main
    thread can drive them with sync urllib (the CLI's transport)."""

    def __init__(self, apps):
        from aiohttp import web

        self._web = web
        self.urls = []
        self._loop = asyncio.new_event_loop()
        self._runners = []
        self._thread = threading.Thread(target=self._loop.run_forever,
                                        daemon=True)
        self._thread.start()
        for app in apps:
            fut = asyncio.run_coroutine_threadsafe(self._start(app),
                                                   self._loop)
            self.urls.append(fut.result(timeout=120))

    async def _start(self, app):
        runner = self._web.AppRunner(app)
        await runner.setup()
        site = self._web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        self._runners.append(runner)
        port = runner.addresses[0][1]
        return f"http://127.0.0.1:{port}"

    def stop(self):
        async def teardown():
            for runner in self._runners:
                await runner.cleanup()

        asyncio.run_coroutine_threadsafe(teardown(),
                                         self._loop).result(timeout=30)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=10)


def _post_json(url, payload, headers=None):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, dict(resp.headers), \
            json.loads(resp.read().decode())


def test_rbt_trace_merges_gateway_and_replicas(tmp_path, monkeypatch,
                                               capsys):
    """Acceptance: one request id stitches gateway + 2 real replicas
    through the real HTTP stack, and `rbt trace` prints one merged,
    clock-ordered timeline."""
    from runbooks_tpu.cli import main as cli
    from runbooks_tpu.serve.api import create_server
    from runbooks_tpu.serve.gateway import create_gateway

    monkeypatch.setenv("RBT_CONTENT_DIR", str(tmp_path))
    cfg = tiny_cfg()
    params = tiny_params(cfg)
    replicas = [create_server(cfg, params, max_slots=2, warmup=True)
                for _ in range(2)]
    host = _AppHost(replicas)
    try:
        gw = create_gateway(
            {f"r{i}": url for i, url in enumerate(host.urls)},
            scrape_interval_s=0)
        gw_host = _AppHost([gw])
        try:
            gw_url = gw_host.urls[0]
            # Client supplies NO id: the gateway mints one and forwards
            # it (plus a minted traceparent) upstream.
            status, headers, _body = _post_json(
                f"{gw_url}/v1/completions",
                {"prompt": "stitch me", "max_tokens": 3})
            assert status == 200
            rid = headers["X-Request-Id"]
            assert rid.startswith("req-")
            assert headers.get("traceparent")
            backend = headers["X-Gateway-Replica"]
            # Gateway access-log line carries the same id (grep parity
            # with the serve tier's access line).
            out = capsys.readouterr().out
            assert f"gateway: access /v1/completions rid={rid}" in out
            assert f"backend={backend}" in out

            # Gateway ring: route decision + proxy span under this id;
            # replica ring: the engine phases under the SAME id.
            with urllib.request.urlopen(
                    f"{gw_url}/debug/flight?request_id={rid}",
                    timeout=30) as resp:
                gw_flight = json.loads(resp.read().decode())
            assert gw_flight["component"] == "gateway"
            assert set(gw_flight["replicas"]) == {"r0", "r1"}
            gw_names = {e["name"] for e in gw_flight["events"]}
            assert {"route_decision", "proxy"} <= gw_names

            # An explicit client id is accepted verbatim (sanitized)
            # and rides to the replica's ring too.
            status, headers2, _ = _post_json(
                f"{gw_url}/v1/completions",
                {"prompt": "stitch me again", "max_tokens": 3},
                headers={"X-Request-Id": "trace-e2e-1"})
            assert headers2["X-Request-Id"] == "trace-e2e-1"
            capsys.readouterr()

            # `rbt trace` against the gateway: merged timeline across
            # the gateway + both replicas, clock-ordered, covering both
            # tiers' phases. (In this in-process test all three apps
            # share ONE ring/identity, so the POD labels all read
            # gateway@<host> and duplicates dedupe; distinct-pod
            # labeling is covered by test_merged_timeline_labels.)
            rc = cli.main(["trace", "trace-e2e-1", "--url", gw_url])
            assert rc == 0
            out = capsys.readouterr().out
            assert "across 3 pod(s)" in out
            for phase in ("route_decision", "proxy", "queue_wait",
                          "prefill", "decode"):
                assert phase in out, f"{phase} missing from timeline:\n{out}"
            # Clock-ordered: offsets are non-decreasing down the table.
            offsets = [float(line.split("ms", 1)[0].lstrip("+"))
                       for line in out.splitlines()
                       if line.startswith("+")]
            assert offsets == sorted(offsets)

            # `rbt incidents` end to end over the same transport.
            _post_json(f"{host.urls[0]}/debug/incident",
                       {"reason": "e2e"})
            rc = cli.main(["incidents", "--url", host.urls[0]])
            assert rc == 0
            out = capsys.readouterr().out
            assert "e2e" in out
        finally:
            gw_host.stop()
    finally:
        host.stop()


def test_merged_timeline_labels_and_dedupe():
    """Pure-function coverage of the cross-pod merge: distinct sources
    keep their component@host labels, events interleave by wall clock,
    and identical events fetched from two sources dedupe to the first."""
    from runbooks_tpu.cli.main import _format_timeline, _merged_timeline

    gw_event = {"name": "proxy", "ph": "X", "ts": 1000.0, "dur": 500.0,
                "pid": 1, "tid": 1,
                "args": {"request_id": "r", "backend": "r0"}}
    rep_event = {"name": "prefill", "ph": "X", "ts": 1200.0, "dur": 100.0,
                 "pid": 2, "tid": 1, "args": {"request_id": "r"}}
    merged = _merged_timeline([
        ("gateway@gw-0", {"events": [gw_event]}),
        ("serve@srv-1/r0", {"events": [rep_event, gw_event]}),
    ])
    assert [(label, e["name"]) for _, label, e in merged] == [
        ("gateway@gw-0", "proxy"), ("serve@srv-1/r0", "prefill")]
    rows = _format_timeline(merged)
    assert rows[0][0] == "+0.0ms" and rows[0][1] == "gateway@gw-0"
    assert rows[1][0] == "+0.2ms" and rows[1][1] == "serve@srv-1/r0"
    assert "backend=r0" in rows[0][4]


# ---------------------------------------------------------------------------
# Controller: SLOViolated onset fires per-replica captures
# ---------------------------------------------------------------------------

def test_slo_onset_fires_incident_capture(tmp_path, monkeypatch):
    """An SLOViolated onset POSTs /debug/incident to every running
    replica (side thread), the bundle lands once (replica-side
    debounce), and .status.lastIncident points at it."""
    from runbooks_tpu.api.types import API_VERSION, Model, Server
    from runbooks_tpu.cloud.base import CommonConfig
    from runbooks_tpu.cloud.local import LocalCloud
    from runbooks_tpu.controller import fleet as fl
    from runbooks_tpu.controller import server as server_mod
    from runbooks_tpu.controller.manager import Ctx, Manager
    from runbooks_tpu.controller.model import ModelReconciler
    from runbooks_tpu.controller.server import INCIDENTS, ServerReconciler
    from runbooks_tpu.k8s import objects as ko
    from runbooks_tpu.k8s.fake import FakeCluster
    from tests.test_gateway import load_sample

    monkeypatch.setenv("RBT_CONTENT_DIR", str(tmp_path))
    fl.FLEET.reset()
    INCIDENTS.reset()

    # Replica stub: the REAL capture behind the real HTTP verb the
    # controller uses (the full aiohttp endpoint is covered above).
    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            assert self.path == "/debug/incident"
            n = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(n) or b"{}")
            path = obs_incident.capture(body.get("reason", "manual"))
            payload = json.dumps({"path": path,
                                  "debounced": path is None}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def log_message(self, *args):
            return

    httpd = HTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        client = FakeCluster()
        cloud = LocalCloud(CommonConfig(
            cluster_name="t", artifact_bucket_url=f"file://{tmp_path}/b",
            registry_url="r.local:5000"))
        from runbooks_tpu.sci.base import FakeSCI

        ctx = Ctx(client=client, cloud=cloud, sci=FakeSCI())
        mgr = Manager(ctx, [ModelReconciler(), ServerReconciler()])
        client.create(Model.new("m", spec={"image": "loader"}).obj)
        client.create(Server.new("srv", spec={
            "image": "img", "model": {"name": "m"},
            "slo": {"queueWaitP90Ms": 50}}).obj)
        client.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "srv-0", "namespace": "default",
                         "labels": {"server": "srv", "role": "run"},
                         "annotations": {fl.METRICS_PORT_ANNOTATION:
                                         str(httpd.server_address[1])}},
            "spec": {}, "status": {"phase": "Running",
                                   "podIP": "127.0.0.1"}})
        mgr.reconcile_until_stable()
        client.mark_job_complete("default", "m-modeller")
        mgr.reconcile_until_stable()

        key = ("Server", "default", "srv")
        # Sustained 400 ms queue wait >> the 50 ms objective: onset.
        fl.FLEET.update(key, load_sample("srv-0", qw_s=0.4, active=4,
                                         queue=6))
        mgr.process_event("Server",
                          client.get(API_VERSION, "Server", "default",
                                     "srv"))
        srv = client.get(API_VERSION, "Server", "default", "srv")
        assert ko.is_condition_true(srv, "SLOViolated")
        assert INCIDENTS.wait(("default", "srv"), timeout_s=15)
        bundles = _bundles(tmp_path)
        assert len(bundles) == 1, bundles
        bundle = json.load(open(bundles[0]))
        assert bundle["reason"].startswith("slo_")
        assert "metrics" in bundle and "memory" in bundle
        # Next reconcile folds the sweep into status.lastIncident.
        mgr.process_event("Server",
                          client.get(API_VERSION, "Server", "default",
                                     "srv"))
        srv = client.get(API_VERSION, "Server", "default", "srv")
        last = ko.deep_get(srv, "status", "lastIncident")
        assert last["reason"].startswith("slo_")
        assert last["bundles"][0]["replica"] == "srv-0"
        assert last["bundles"][0]["path"] == bundles[0]

        # Clear, then re-violate inside the debounce window: the onset
        # fires again, the REPLICA debounces, still exactly one bundle.
        fl.FLEET.update(key, load_sample("srv-0", qw_s=0.0, active=0,
                                         queue=0))
        mgr.process_event("Server",
                          client.get(API_VERSION, "Server", "default",
                                     "srv"))
        assert not ko.is_condition_true(
            client.get(API_VERSION, "Server", "default", "srv"),
            "SLOViolated")
        fl.FLEET.update(key, load_sample("srv-0", qw_s=0.4, active=4,
                                         queue=6))
        mgr.process_event("Server",
                          client.get(API_VERSION, "Server", "default",
                                     "srv"))
        assert INCIDENTS.wait(("default", "srv"), timeout_s=15)
        assert len(_bundles(tmp_path)) == 1
        result = server_mod.INCIDENTS.take(("default", "srv"))
        assert result["bundles"][0].get("debounced") is True
    finally:
        httpd.shutdown()
        httpd.server_close()
        fl.FLEET.reset()
        INCIDENTS.reset()
