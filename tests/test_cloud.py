"""Cloud + TPU resources unit tests (reference analogs:
internal/cloud/common_test.go, gcp_test.go, internal/resources/
resources_test.go)."""

import pytest

from runbooks_tpu.api.types import Model
from runbooks_tpu.cloud.base import (
    BucketMount,
    CommonConfig,
    image_name,
    image_tag_for,
    object_bucket_path,
    parse_bucket_url,
)
from runbooks_tpu.cloud.gcp import GCPCloud, GCPConfig
from runbooks_tpu.cloud.local import LocalCloud
from runbooks_tpu.cloud.resources import (
    TPU_TYPES,
    apply_tpu_resources,
    distributed_env,
    fan_out_job,
    parse_tpu,
)


def test_bucket_path_is_deterministic_md5():
    m = Model.new("m1", namespace="ns1")
    p1 = object_bucket_path("c1", m)
    p2 = object_bucket_path("c1", Model.new("m1", namespace="ns1"))
    assert p1 == p2 and len(p1) == 32
    assert p1 != object_bucket_path("c2", m)          # cluster-scoped
    assert p1 != object_bucket_path("c1", Model.new("m2", namespace="ns1"))


def test_image_naming_and_tags():
    cfg = CommonConfig(cluster_name="clu", registry_url="reg.io/p/r")
    m = Model.new("my-model", namespace="team")
    assert image_name(cfg, m, "abc") == "reg.io/p/r/clu-model-team-my-model:abc"
    assert image_tag_for(m) == "latest"
    m.spec["build"] = {"git": {"url": "u", "branch": "dev"}}
    assert image_tag_for(m) == "dev"
    m.spec["build"] = {"git": {"url": "u", "tag": "v1", "branch": "dev"}}
    assert image_tag_for(m) == "v1"
    m.spec["build"] = {"upload": {"md5checksum": "f" * 32}}
    assert image_tag_for(m) == "f" * 32


def test_parse_bucket_url():
    assert parse_bucket_url("gs://b/p/x") == ("gs", "b/p/x")
    with pytest.raises(ValueError):
        parse_bucket_url("no-scheme")


@pytest.mark.parametrize("tpu,chips,hosts", [
    ({"type": "v5e", "topology": "1x1"}, 1, 1),
    ({"type": "v5e", "topology": "2x2"}, 4, 1),
    ({"type": "v5e", "topology": "2x4"}, 8, 2),
    ({"type": "v5e", "topology": "4x4"}, 16, 4),
    ({"type": "v5p", "topology": "2x2x1"}, 4, 1),
    ({"type": "v5p", "topology": "2x2x2"}, 8, 2),
    ({"type": "v5p", "topology": "4x4x4"}, 64, 16),
    ({"type": "v6e", "topology": "2x4"}, 8, 2),
])
def test_tpu_topology_math(tpu, chips, hosts):
    s = parse_tpu(tpu)
    assert s.chips == chips and s.hosts == hosts
    assert s.accelerator == TPU_TYPES[tpu["type"]]["accelerator"]


def test_tpu_validation_errors():
    with pytest.raises(ValueError, match="unknown tpu type"):
        parse_tpu({"type": "v99", "topology": "2x2"})
    with pytest.raises(ValueError, match="3-dimensional"):
        parse_tpu({"type": "v5p", "topology": "2x2"})
    with pytest.raises(ValueError, match="2-dimensional"):
        parse_tpu({"type": "v5e", "topology": "2x2x2"})
    with pytest.raises(ValueError, match="invalid tpu topology"):
        parse_tpu({"type": "v5e", "topology": "axb"})


def test_fan_out_env_and_spot():
    slice_ = parse_tpu({"type": "v5e", "topology": "4x4"})
    pod_spec = {"containers": [{"name": "model"}]}
    apply_tpu_resources(pod_spec, "model", slice_, spot=True)
    assert pod_spec["nodeSelector"]["cloud.google.com/gke-spot"] == "true"
    assert pod_spec["tolerations"][0]["key"] == "cloud.google.com/gke-spot"

    env = distributed_env("job", "svc", "ns", slice_)
    env_map = {e["name"]: e for e in env}
    assert env_map["JAX_COORDINATOR_ADDRESS"]["value"] == \
        "job-0.svc.ns.svc.cluster.local:8476"
    assert env_map["JAX_NUM_PROCESSES"]["value"] == "4"
    hostnames = env_map["TPU_WORKER_HOSTNAMES"]["value"].split(",")
    assert len(hostnames) == 4

    job = {"metadata": {"name": "job", "namespace": "ns"},
           "spec": {"template": {"spec": pod_spec}}}
    svc = fan_out_job(job, slice_)
    assert svc["spec"]["clusterIP"] == "None"
    assert job["spec"]["completionMode"] == "Indexed"
    assert job["spec"]["completions"] == 4


def test_gcp_mounts_gcsfuse_csi():
    cloud = GCPCloud(GCPConfig(common=CommonConfig(
        cluster_name="c", artifact_bucket_url="gs://my-bucket",
        registry_url="reg", principal="gsa@p.iam.gserviceaccount.com")))
    m = Model.new("m")
    assert cloud.object_artifact_url(m).startswith("gs://my-bucket/")

    pod_meta, pod_spec = {}, {"containers": [{"name": "model"}]}
    cloud.mount_bucket(pod_meta, pod_spec, m,
                       BucketMount("artifacts", "artifacts", read_only=False))
    assert pod_meta["annotations"]["gke-gcsfuse/volumes"] == "true"
    vol = pod_spec["volumes"][0]
    assert vol["csi"]["driver"] == "gcsfuse.csi.storage.gke.io"
    assert vol["csi"]["volumeAttributes"]["bucketName"] == "my-bucket"
    vm = pod_spec["containers"][0]["volumeMounts"][0]
    assert vm["mountPath"] == "/content/artifacts"
    assert vm["subPath"].endswith("/artifacts")
    assert pod_spec["securityContext"]["fsGroup"] == 3003

    sa = {"metadata": {"name": "modeller"}}
    principal, bound = cloud.get_principal(sa)
    assert not bound
    cloud.associate_principal(sa)
    _, bound = cloud.get_principal(sa)
    assert bound


def test_local_cloud_hostpath_mounts():
    cloud = LocalCloud(CommonConfig(cluster_name="c"))
    m = Model.new("m")
    pod_meta, pod_spec = {}, {"containers": [{"name": "model"}]}
    cloud.mount_bucket(pod_meta, pod_spec, m, BucketMount("artifacts", "data"))
    vol = pod_spec["volumes"][0]
    assert "hostPath" in vol
    assert pod_spec["containers"][0]["volumeMounts"][0]["readOnly"]


def test_metadata_autodetect(monkeypatch):
    """CLOUD unset -> GCE metadata probe decides gcp vs local, and gcp picks
    up project/cluster identity from metadata attributes (reference:
    internal/cloud/cloud.go:48-85, gcp.go:28-71)."""
    import http.server
    import threading

    class FakeMetadata(http.server.BaseHTTPRequestHandler):
        attrs = {
            "/computeMetadata/v1/project/project-id": "proj-42",
            "/computeMetadata/v1/instance/attributes/cluster-name": "tpu-c",
            "/computeMetadata/v1/instance/attributes/cluster-location":
                "us-central2-b",
        }

        def do_GET(self):  # noqa: N802
            if self.headers.get("Metadata-Flavor") != "Google":
                self.send_response(403)
                self.end_headers()
                return
            body = self.attrs.get(self.path, "")
            if self.path != "/computeMetadata/v1/" and not body:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Metadata-Flavor", "Google")
            self.end_headers()
            self.wfile.write(body.encode())

        def log_message(self, *args):
            return

    srv = http.server.HTTPServer(("127.0.0.1", 0), FakeMetadata)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        host = f"127.0.0.1:{srv.server_address[1]}"
        monkeypatch.setenv("GCE_METADATA_HOST", host)
        monkeypatch.delenv("CLOUD", raising=False)
        monkeypatch.delenv("PROJECT_ID", raising=False)
        monkeypatch.delenv("CLUSTER_NAME", raising=False)
        monkeypatch.setenv("ARTIFACT_BUCKET_URL", "gs://b")
        monkeypatch.setenv("SCI_ADDRESS", "fake")
        monkeypatch.setenv("STANDALONE", "1")

        from runbooks_tpu.controller.main import build_ctx

        ctx = build_ctx()
        assert ctx.cloud.name == "gcp"
        assert ctx.cloud.config.project_id == "proj-42"
        assert ctx.cloud.config.common.cluster_name == "tpu-c"
        assert ctx.cloud.config.cluster_location == "us-central2-b"

        # Probe failure (closed port): STANDALONE demo mode falls back to
        # local; otherwise it is fatal like the reference (cloud.go:60-68)
        # — silently coming up local on real GKE misreconciles everything.
        srv2 = http.server.HTTPServer(("127.0.0.1", 0), FakeMetadata)
        port2 = srv2.server_address[1]
        srv2.server_close()
        monkeypatch.setenv("GCE_METADATA_HOST", f"127.0.0.1:{port2}")
        ctx = build_ctx()
        assert ctx.cloud.name == "local"
        monkeypatch.delenv("STANDALONE")
        with pytest.raises(RuntimeError, match="unable to determine cloud"):
            build_ctx()
        monkeypatch.setenv("STANDALONE", "1")

        # A reachable metadata server missing required attributes is also
        # fatal (auto_configure must not return '' project ids).
        class Empty(FakeMetadata):
            attrs = {}

        srv3 = http.server.HTTPServer(("127.0.0.1", 0), Empty)
        threading.Thread(target=srv3.serve_forever, daemon=True).start()
        try:
            monkeypatch.setenv("GCE_METADATA_HOST",
                               f"127.0.0.1:{srv3.server_address[1]}")
            with pytest.raises(RuntimeError, match="failed to get project"):
                build_ctx()
        finally:
            srv3.shutdown()
            srv3.server_close()
    finally:
        srv.shutdown()
        srv.server_close()
