"""Fleet telemetry plane tests (ISSUE 6).

Covers: the controller scraper against a real (fake-replica) /metrics
endpoint including histogram re-exposition and a down replica;
SLOViolated condition transitions in both directions across reconciles;
request-id propagation end to end (header in -> engine spans -> header
out); trace.jsonl rotation; `rbt top`; the metrics-catalog drift check;
and the bench regression gate helper.
"""

import dataclasses
import json
import os
import re
import threading

import pytest

from runbooks_tpu.api import conditions as cond
from runbooks_tpu.api.types import API_VERSION, Model, Server
from runbooks_tpu.cloud.base import CommonConfig
from runbooks_tpu.cloud.local import LocalCloud
from runbooks_tpu.controller import fleet as fl
from runbooks_tpu.controller.common import validate_slo
from runbooks_tpu.controller.manager import Ctx, Manager
from runbooks_tpu.controller.model import ModelReconciler
from runbooks_tpu.controller.server import ServerReconciler
from runbooks_tpu.k8s import objects as ko
from runbooks_tpu.k8s.fake import FakeCluster
from runbooks_tpu.obs import metrics as obs_metrics
from runbooks_tpu.obs import trace as obs_trace
from runbooks_tpu.obs.metrics import CATALOG, Registry, serve_metrics
from runbooks_tpu.sci.base import FakeSCI


# ---------------------------------------------------------------------------
# Harness
# ---------------------------------------------------------------------------

@pytest.fixture()
def harness(tmp_path):
    client = FakeCluster()
    cloud = LocalCloud(CommonConfig(
        cluster_name="testcluster",
        artifact_bucket_url=f"file://{tmp_path}/bucket",
        registry_url="registry.local:5000"))
    ctx = Ctx(client=client, cloud=cloud, sci=FakeSCI())
    mgr = Manager(ctx, [ModelReconciler(), ServerReconciler()])
    return client, ctx, mgr


@pytest.fixture(autouse=True)
def clean_fleet_state():
    fl.FLEET.reset()
    yield
    fl.FLEET.reset()


def make_pod(client, name, labels, port, ip="127.0.0.1"):
    client.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": "default",
                     "labels": dict(labels, role="run"),
                     "annotations": {fl.METRICS_PORT_ANNOTATION: str(port)}},
        "spec": {"containers": [{"name": "c"}]},
        "status": {"phase": "Running", "podIP": ip},
    })


def replica_registry(ttft_values=(0.02, 0.05, 0.4), requests=10, failed=0,
                     tokens=500, slots=3, queue=1):
    reg = Registry()
    reg.set_counter("serve_requests_total", requests)
    reg.set_counter("serve_requests_failed_total", failed)
    reg.set_counter("serve_tokens_generated_total", tokens)
    reg.set_gauge("serve_active_slots", slots)
    reg.set_gauge("serve_queue_depth", queue)
    for v in ttft_values:
        reg.observe("serve_ttft_seconds", v)
        reg.observe("serve_queue_wait_seconds", v / 10)
    return reg


# ---------------------------------------------------------------------------
# Exposition parser (scrape side of obs/metrics.py)
# ---------------------------------------------------------------------------

def test_parse_exposition_round_trip():
    reg = replica_registry()
    reg.set_gauge("weird", 1, path='a"b\\c\nd')
    families = obs_metrics.parse_exposition(reg.render())
    assert families["serve_requests_total"].type == "counter"
    assert families["serve_requests_total"].total() == 10.0
    assert families["serve_active_slots"].value() == 3.0
    # Escaped label values round-trip exactly.
    assert families["weird"].value(path='a"b\\c\nd') == 1.0
    hist = families["serve_ttft_seconds"].merged_histogram()
    assert hist.count == 3
    assert hist.sum == pytest.approx(0.47)
    # The 0.4 observation sits in the 0.5 bucket; p99 lands inside it.
    assert 0.25 <= hist.quantile(0.99) <= 0.5


def test_set_histogram_mirrors_bucket_exactly():
    src = Registry()
    for v in (0.002, 0.03, 7.0):
        src.observe("lat_seconds", v)
    parsed = obs_metrics.parse_exposition(src.render())["lat_seconds"]
    hist = parsed.merged_histogram()
    dst = Registry()
    dst.set_histogram("lat_seconds", hist.bounds, hist.cumulative,
                      hist.count, hist.sum, replica="p0")
    out = obs_metrics.parse_exposition(dst.render())["lat_seconds"]
    mirrored = out.histograms[(("replica", "p0"),)]
    assert mirrored.cumulative == hist.cumulative
    assert mirrored.count == 3
    assert mirrored.sum == pytest.approx(hist.sum)


def test_registry_drop_series():
    reg = Registry()
    reg.set_gauge("g", 1, replica="a", kind="Server")
    reg.set_counter("c_total", 2, replica="a")
    reg.observe("h_seconds", 0.1, replica="a")
    reg.set_gauge("g", 1, replica="b", kind="Server")
    assert reg.drop_series(replica="a") == 3
    text = reg.render()
    assert 'replica="a"' not in text
    assert 'replica="b"' in text


# ---------------------------------------------------------------------------
# Controller scraper
# ---------------------------------------------------------------------------

def test_scraper_mirrors_replica_metrics_and_marks_down(harness):
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    reg_a = replica_registry(requests=10, tokens=500)
    reg_b = replica_registry(requests=4, tokens=100, slots=1, failed=2)
    httpd_a = serve_metrics(0, reg_a)
    httpd_b = serve_metrics(0, reg_b)
    make_pod(client, "srv-a", {"server": "srv"}, httpd_a.server_address[1])
    make_pod(client, "srv-b", {"server": "srv"}, httpd_b.server_address[1])

    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry)
    try:
        assert scraper.scrape_once() == 2
        text = registry.render()
        # Per-replica mirrored series with {kind, name, replica} labels.
        for rep, val in (("srv-a", 10.0), ("srv-b", 4.0)):
            assert (f'serve_requests_total{{kind="Server",name="srv",'
                    f'namespace="default",replica="{rep}"}} {val}') in text
        # Histograms re-expose bucket-exactly (cumulative le series).
        assert re.search(
            r'serve_ttft_seconds_bucket\{[^}]*replica="srv-a"[^}]*\} \d',
            text)
        # Freshness/liveness gauges.
        assert 'fleet_scrape_up{kind="Server",name="srv",' \
               'namespace="default",replica="srv-a"} 1' in text
        assert "fleet_scrape_age_seconds" in text
        # Aggregated summary merges across replicas.
        summary = state.server_summary("default", "srv")
        assert summary["replicas"] == 2 and summary["replicasUp"] == 2
        assert summary["activeSlots"] == 4
        assert summary["requestsTotal"] == 14
        assert summary["errorRatePct"] == pytest.approx(2 / 14 * 100, 0.01)
        assert summary["ttftP99Ms"] > 0

        # Replica b dies: next sweep marks it down, keeps a up.
        httpd_b.shutdown()
        httpd_b.server_close()
        assert scraper.scrape_once() == 1
        text = registry.render()
        assert 'fleet_scrape_up{kind="Server",name="srv",' \
               'namespace="default",replica="srv-b"} 0' in text
        assert 'fleet_scrape_up{kind="Server",name="srv",' \
               'namespace="default",replica="srv-a"} 1' in text
        summary = state.server_summary("default", "srv")
        assert summary["replicasUp"] == 1
        assert summary["activeSlots"] == 3  # only the live replica counts

        # Pod deleted entirely: its mirrored series are dropped, not
        # frozen at their last values.
        client.delete("v1", "Pod", "default", "srv-b")
        scraper.scrape_once()
        assert 'replica="srv-b"' not in registry.render()
    finally:
        httpd_a.shutdown()
        httpd_a.server_close()


def test_scraper_tokens_per_sec_rate(harness):
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    reg = replica_registry(tokens=1000)
    httpd = serve_metrics(0, reg)
    make_pod(client, "srv-a", {"server": "srv"}, httpd.server_address[1])
    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry)
    try:
        scraper.scrape_once()
        assert state.server_summary("default", "srv")["tokensPerSec"] == 0.0
        reg.set_counter("serve_tokens_generated_total", 2000)
        import time

        time.sleep(0.05)
        scraper.scrape_once()
        tps = state.server_summary("default", "srv")["tokensPerSec"]
        assert tps > 0, "second scrape should compute a token rate"
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_scraper_training_pod_summary(harness):
    client, ctx, _ = harness
    client.create(Model.new("m", spec={"image": "trainer"}).obj)
    reg = Registry()
    reg.set_gauge("train_step", 40)
    reg.set_gauge("train_loss", 2.125)
    reg.set_gauge("train_goodput_ratio", 0.95)
    httpd = serve_metrics(0, reg)
    make_pod(client, "m-modeller-0", {"model": "m"},
             httpd.server_address[1])
    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry)
    try:
        assert scraper.scrape_once() == 1
        summary = state.model_summary("default", "m")
        assert summary == {"replicas": 1, "replicasUp": 1, "step": 40,
                           "loss": 2.125, "goodput": 0.95}
        assert 'train_step{kind="Model"' in registry.render()
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_modeller_job_exposes_metrics_port(harness):
    client, ctx, mgr = harness
    client.create(Model.new("m", spec={"image": "trainer"}).obj)
    mgr.reconcile_until_stable()
    job = client.get("batch/v1", "Job", "default", "m-modeller")
    container = job["spec"]["template"]["spec"]["containers"][0]
    assert {"name": "metrics", "containerPort": 8080} in container["ports"]
    env = {e["name"]: e.get("value") for e in container["env"]}
    assert env["RBT_METRICS_PORT"] == "8080"


# ---------------------------------------------------------------------------
# SLO conditions + status telemetry
# ---------------------------------------------------------------------------

def ttft_sample(replica, ttft_s, n=10, extra=None):
    """A synthetic up-replica sample whose merged TTFT p99 ~= ttft_s."""
    fam = obs_metrics.ParsedFamily("serve_ttft_seconds", "histogram")
    hist = obs_metrics.ParsedHistogram()
    hist.bounds = [b for b in obs_metrics.DEFAULT_BUCKETS]
    import bisect

    idx = bisect.bisect_left(hist.bounds, ttft_s)
    cum = []
    acc = 0
    for i in range(len(hist.bounds)):
        if i == idx:
            acc = n
        cum.append(acc)
    hist.cumulative = cum
    hist.count = n
    hist.sum = ttft_s * n
    fam.histograms[()] = hist
    fams = {"serve_ttft_seconds": fam}
    slots = obs_metrics.ParsedFamily("serve_active_slots", "gauge")
    slots.samples[()] = 2.0
    fams["serve_active_slots"] = slots
    reqs = obs_metrics.ParsedFamily("serve_requests_total", "counter")
    reqs.samples[()] = float(n)
    fams["serve_requests_total"] = reqs
    if extra:
        fams.update(extra)
    return fl.ReplicaSample(replica, up=True, last_success=0.0,
                            families=fams)


def test_slo_violated_condition_transitions(harness):
    client, ctx, mgr = harness
    client.create(Model.new("m", spec={"image": "loader"}).obj)
    client.create(Server.new("srv", spec={
        "image": "img", "model": {"name": "m"},
        "slo": {"ttftP99Ms": 100}}).obj)
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "m-modeller")
    mgr.reconcile_until_stable()
    client.mark_deployment_ready("default", "srv")
    mgr.reconcile_until_stable()

    # No scrape data yet: condition present but False/NoTelemetry.
    srv = client.get(API_VERSION, "Server", "default", "srv")
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "False" and c["reason"] == cond.REASON_SLO_NO_DATA

    from runbooks_tpu.controller.metrics import REGISTRY

    before = REGISTRY.counter_value(
        "controller_slo_violations_total", server="srv",
        objective=cond.REASON_SLO_TTFT)

    # Violating traffic lands in the fleet state -> ONE reconcile flips
    # the condition (acceptance: within one reconcile).
    fl.FLEET.update(("Server", "default", "srv"),
                    ttft_sample("srv-pod", 0.4))
    mgr.process_event("Server",
                      client.get(API_VERSION, "Server", "default", "srv"))
    srv = client.get(API_VERSION, "Server", "default", "srv")
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "True"
    assert c["reason"] == cond.REASON_SLO_TTFT
    assert "ttftP99Ms" in c["message"] and "100" in c["message"]
    # Onset counted once.
    assert REGISTRY.counter_value(
        "controller_slo_violations_total", server="srv",
        objective=cond.REASON_SLO_TTFT) == before + 1
    # .status.telemetry carries the live load summary.
    telem = ko.deep_get(srv, "status", "telemetry")
    assert telem["activeSlots"] == 2
    assert telem["ttftP99Ms"] > 100

    # Load drops -> the condition sheds on the next reconcile.
    fl.FLEET.update(("Server", "default", "srv"),
                    ttft_sample("srv-pod", 0.01))
    mgr.process_event("Server",
                      client.get(API_VERSION, "Server", "default", "srv"))
    srv = client.get(API_VERSION, "Server", "default", "srv")
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "False" and c["reason"] == cond.REASON_SLO_MET
    # No new onset counted.
    assert REGISTRY.counter_value(
        "controller_slo_violations_total", server="srv",
        objective=cond.REASON_SLO_TTFT) == before + 1


def test_slo_error_rate_objective(harness):
    client, ctx, mgr = harness
    client.create(Model.new("m", spec={"image": "loader"}).obj)
    client.create(Server.new("srv", spec={
        "image": "img", "model": {"name": "m"},
        "slo": {"errorRatePct": 5}}).obj)
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "m-modeller")
    failed = obs_metrics.ParsedFamily("serve_requests_failed_total",
                                      "counter")
    failed.samples[()] = 3.0
    fl.FLEET.update(
        ("Server", "default", "srv"),
        ttft_sample("p0", 0.01,
                    extra={"serve_requests_failed_total": failed}))
    mgr.reconcile_until_stable()
    srv = client.get(API_VERSION, "Server", "default", "srv")
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "True"
    assert c["reason"] == cond.REASON_SLO_ERROR_RATE


def test_slo_holds_verdict_through_total_outage(harness):
    """Every replica down: the last SLO verdict HOLDS (an outage must
    not clear an active violation), and the dead replica's token-rate
    gauge resets so it never reads as still serving."""
    client, ctx, mgr = harness
    client.create(Model.new("m", spec={"image": "loader"}).obj)
    client.create(Server.new("srv", spec={
        "image": "img", "model": {"name": "m"},
        "slo": {"ttftP99Ms": 100}}).obj)
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "m-modeller")
    fl.FLEET.update(("Server", "default", "srv"),
                    ttft_sample("srv-pod", 0.4))
    mgr.reconcile_until_stable()
    srv = client.get(API_VERSION, "Server", "default", "srv")
    assert ko.is_condition_true(srv, cond.SLO_VIOLATED)

    # Replica goes down (pod still present): verdict unchanged.
    down = dataclasses.replace(
        fl.FLEET.get_sample(("Server", "default", "srv"), "srv-pod"),
        up=False)
    fl.FLEET.update(("Server", "default", "srv"), down)
    mgr.process_event("Server",
                      client.get(API_VERSION, "Server", "default", "srv"))
    srv = client.get(API_VERSION, "Server", "default", "srv")
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "True" and c["reason"] == cond.REASON_SLO_TTFT


def test_down_replica_token_rate_resets(harness):
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    reg = replica_registry(tokens=1000)
    httpd = serve_metrics(0, reg)
    make_pod(client, "srv-a", {"server": "srv"}, httpd.server_address[1])
    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry)
    try:
        scraper.scrape_once()
        reg.set_counter("serve_tokens_generated_total", 5000)
        import time

        time.sleep(0.05)
        scraper.scrape_once()
        fam = obs_metrics.parse_exposition(
            registry.render())["fleet_tokens_per_sec"]
        assert fam.total() > 0
    finally:
        httpd.shutdown()
        httpd.server_close()
    # Endpoint dead, pod still Running: the rate gauge must drop to 0.
    scraper.scrape_once()
    fam = obs_metrics.parse_exposition(
        registry.render())["fleet_tokens_per_sec"]
    assert fam.total() == 0.0


def test_scraper_survives_label_collisions(harness):
    """A scraped exposition already carrying kind/replica labels (a
    process sharing its registry with a controller) must mirror without
    a duplicate-kwarg crash — the scraped pod's identity wins."""
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    reg = Registry()
    reg.set_gauge("serve_active_slots", 7, kind="Server", name="other",
                  namespace="elsewhere", replica="other-pod")
    reg.observe("serve_ttft_seconds", 0.1, kind="Server",
                replica="other-pod")
    httpd = serve_metrics(0, reg)
    make_pod(client, "srv-a", {"server": "srv"}, httpd.server_address[1])
    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry)
    try:
        assert scraper.scrape_once() == 1
    finally:
        httpd.shutdown()
        httpd.server_close()
    text = registry.render()
    assert 'serve_active_slots{kind="Server",name="srv",' \
           'namespace="default",replica="srv-a"} 7' in text


def test_rbt_top_separates_namespaces(capsys):
    """Same-named Servers in two namespaces must not blend each other's
    series in the top table."""
    from runbooks_tpu.cli import main as cli

    reg = Registry()
    for ns, slots in (("a", 1), ("b", 5)):
        lbl = dict(kind="Server", namespace=ns, name="chat",
                   replica=f"chat-{ns}")
        reg.set_gauge("fleet_scrape_up", 1, **lbl)
        reg.set_gauge("fleet_scrape_age_seconds", 0.0, **lbl)
        reg.set_gauge("serve_active_slots", slots, **lbl)
        reg.set_gauge("fleet_slo_violated", 1 if ns == "b" else 0,
                      kind="Server", namespace=ns, name="chat")
    httpd = serve_metrics(0, reg)
    try:
        assert cli.main(["top", "--once",
                         "--url",
                         f"http://127.0.0.1:{httpd.server_address[1]}"]) \
            == 0
    finally:
        httpd.shutdown()
        httpd.server_close()
    out = capsys.readouterr().out
    row_a = next(ln for ln in out.splitlines() if "chat-a" in ln)
    row_b = next(ln for ln in out.splitlines() if "chat-b" in ln)
    assert "slots=1" in row_a and "ok" in row_a
    assert "slots=5" in row_b and "VIOLATED" in row_b


def test_invalid_slo_surfaces_condition(harness):
    client, ctx, mgr = harness
    client.create(Server.new("bad", spec={
        "image": "img", "model": {"name": "m"},
        "slo": {"ttftP99": 100}}).obj)  # typo'd objective name
    mgr.reconcile_until_stable()
    srv = client.get(API_VERSION, "Server", "default", "bad")
    c = ko.get_condition(srv, cond.SERVING)
    assert c["status"] == "False"
    assert c["reason"] == cond.REASON_INVALID_PARAMS
    assert "ttftP99" in c["message"]

    assert validate_slo(None) is None
    assert validate_slo({"ttftP99Ms": 100}) is None
    assert "not a number" in validate_slo({"ttftP99Ms": "fast"})
    assert "> 0" in validate_slo({"queueWaitP90Ms": 0})
    assert "unknown objective" in validate_slo({"p99": 1})


def test_model_status_telemetry(harness):
    client, ctx, mgr = harness
    client.create(Model.new("m", spec={"image": "trainer"}).obj)
    step = obs_metrics.ParsedFamily("train_step", "gauge")
    step.samples[()] = 40.0
    loss = obs_metrics.ParsedFamily("train_loss", "gauge")
    loss.samples[()] = 2.5
    fl.FLEET.update(("Model", "default", "m"), fl.ReplicaSample(
        "m-0", up=True, last_success=0.0,
        families={"train_step": step, "train_loss": loss}))
    mgr.reconcile_until_stable()
    m = client.get(API_VERSION, "Model", "default", "m")
    telem = ko.deep_get(m, "status", "telemetry")
    assert telem["step"] == 40 and telem["loss"] == 2.5


# ---------------------------------------------------------------------------
# Request-scoped tracing end to end
# ---------------------------------------------------------------------------

def tiny_cfg():
    from runbooks_tpu.models.config import get_config

    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32")


def test_request_id_propagation_end_to_end(tmp_path, monkeypatch, capsys):
    """Header in -> queue/prefill/decode spans -> header out, plus the
    generated-id, traceparent, and access-log paths."""
    import asyncio

    import jax
    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.models.transformer import init_params
    from runbooks_tpu.serve.api import create_server

    monkeypatch.setenv("RBT_TRACE", "1")
    path = str(tmp_path / "trace.jsonl")
    obs_trace.configure(path)
    cfg = tiny_cfg()
    app = create_server(cfg, init_params(cfg, jax.random.key(0)),
                        max_slots=2)
    tp_in = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post(
                "/v1/completions",
                json={"prompt": "hello", "max_tokens": 4},
                headers={"X-Request-Id": "my-req-1", "traceparent": tp_in})
            assert r.status == 200
            assert r.headers["X-Request-Id"] == "my-req-1"
            tp_out = r.headers["traceparent"]
            assert tp_out.startswith("00-" + "ab" * 16 + "-")
            assert tp_out != tp_in  # fresh parent-id for the hop
            # No header: an id is generated and still returned.
            r2 = await client.post("/v1/completions", json={
                "prompt": "hi", "max_tokens": 2})
            assert r2.headers["X-Request-Id"].startswith("req-")
            # SSE streaming carries the id on the stream response.
            r3 = await client.post(
                "/v1/completions",
                json={"prompt": "hey", "max_tokens": 2, "stream": True},
                headers={"X-Request-Id": "sse-req"})
            assert r3.headers["X-Request-Id"] == "sse-req"
            await r3.text()

    try:
        asyncio.run(drive())
    finally:
        obs_trace.close()
        obs_trace.configure(None)

    events = []
    with open(path) as f:
        assert f.readline().strip() == "["
        for line in f:
            line = line.strip().rstrip(",")
            if line:
                events.append(json.loads(line))
    by_phase = {}
    for e in events:
        args = e.get("args", {})
        rids = list(args.get("request_ids", []))
        if "request_id" in args:
            rids.append(args["request_id"])
        if "my-req-1" in rids:
            by_phase[e["name"]] = by_phase.get(e["name"], 0) + 1
    # The request's trace covers its queue wait, its prefill, and every
    # decode chunk it was active in (4 tokens = 1 prefill + 3 decodes).
    assert by_phase.get("queue_wait") == 1
    assert by_phase.get("prefill") == 1
    assert by_phase.get("decode", 0) >= 3
    # Access log lines carry the ids.
    out = capsys.readouterr().out
    assert "rid=my-req-1" in out and "rid=sse-req" in out


def test_request_scope_sanitizes_hostile_ids():
    from runbooks_tpu.serve.api import request_scope

    rid, tp = request_scope({"X-Request-Id": "ok-id\r\nInjected: 1"})
    assert "\r" not in rid and "\n" not in rid and " " not in rid
    assert rid.startswith("ok-id")
    rid, tp = request_scope({})
    assert rid.startswith("req-") and tp is None
    rid, tp = request_scope({"traceparent": "00-" + "0f" * 16 + "-"
                             + "11" * 8 + "-00"})
    assert rid == "0f" * 16
    assert tp is not None and tp.startswith("00-" + "0f" * 16)


# ---------------------------------------------------------------------------
# Trace rotation (satellite)
# ---------------------------------------------------------------------------

def test_trace_rotation_caps_size(tmp_path, monkeypatch):
    monkeypatch.setenv("RBT_TRACE", "1")
    monkeypatch.setenv("RBT_TRACE_MAX_MB", "0.0005")  # ~512 bytes
    path = str(tmp_path / "trace.jsonl")
    obs_trace.configure(path)
    try:
        for i in range(80):
            with obs_trace.span("phase", i=i):
                pass
    finally:
        obs_trace.close()
        obs_trace.configure(None)
    assert os.path.exists(path + ".1"), "rotation never happened"
    cap = int(0.0005 * 2**20)
    # Both generations stay line-parseable with their own '[' header and
    # within a write of the cap.
    for p in (path, path + ".1"):
        assert os.path.getsize(p) <= cap + 200
        with open(p) as f:
            assert f.readline().strip() == "["
            for line in f:
                line = line.strip().rstrip(",")
                if line:
                    json.loads(line)


# ---------------------------------------------------------------------------
# rbt top + rbt get telemetry column
# ---------------------------------------------------------------------------

def test_rbt_top_once_against_controller_metrics(capsys):
    from runbooks_tpu.cli import main as cli

    reg = Registry()
    lbl = dict(kind="Server", namespace="default", name="srv",
               replica="srv-1")
    reg.set_gauge("fleet_scrape_up", 1, **lbl)
    reg.set_gauge("fleet_scrape_age_seconds", 0.0, **lbl)
    reg.set_gauge("fleet_tokens_per_sec", 42.5, **lbl)
    reg.set_gauge("serve_active_slots", 3, **lbl)
    reg.set_gauge("serve_queue_depth", 1, **lbl)
    reg.set_histogram("serve_ttft_seconds", [0.05, 0.1, 0.25],
                      [0, 5, 10], 10, 1.5, **lbl)
    reg.set_gauge("fleet_slo_violated", 1, kind="Server",
                  namespace="default", name="srv")
    mlbl = dict(kind="Model", namespace="default", name="m", replica="m-0")
    reg.set_gauge("fleet_scrape_up", 0, **mlbl)
    reg.set_gauge("fleet_scrape_age_seconds", 33.0, **mlbl)
    reg.set_gauge("train_step", 40, **mlbl)
    reg.set_gauge("train_loss", 2.125, **mlbl)
    httpd = serve_metrics(0, reg)
    try:
        rc = cli.main(["top", "--once",
                       "--url",
                       f"http://127.0.0.1:{httpd.server_address[1]}"])
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert rc == 0
    out = capsys.readouterr().out
    srv_line = next(ln for ln in out.splitlines()
                    if ln.startswith("servers/srv"))
    assert "srv-1" in srv_line and "yes" in srv_line
    assert "VIOLATED" in srv_line
    assert "slots=3" in srv_line and "queue=1" in srv_line
    assert "ttft99=" in srv_line and "tok/s=42.5" in srv_line
    m_line = next(ln for ln in out.splitlines()
                  if ln.startswith("models/m"))
    assert "NO" in m_line and "33s" in m_line
    assert "step=40" in m_line and "loss=2.125" in m_line


def test_rbt_top_once_from_crd_status(monkeypatch, capsys):
    from runbooks_tpu.cli import main as cli

    client = FakeCluster()
    srv = Server.new("srv", spec={"image": "x"})
    srv.obj["status"] = {
        "ready": True,
        "telemetry": {"activeSlots": 2, "queueWaitP90Ms": 12.0,
                      "ttftP99Ms": 88.0, "tokensPerSec": 120.5,
                      "replicas": 2, "replicasUp": 2},
        "conditions": [{"type": "SLOViolated", "status": "True",
                        "reason": "TTFTP99AboveTarget", "message": ""}],
    }
    client.create(srv.obj)
    monkeypatch.setattr(cli, "make_client", lambda args: client)
    assert cli.main(["top", "--once"]) == 0
    out = capsys.readouterr().out
    line = next(ln for ln in out.splitlines()
                if ln.startswith("servers/srv"))
    assert "VIOLATED" in line
    assert "slots=2" in line and "ttft99=88.0ms" in line
    assert "up=2/2" in line


def test_rbt_get_shows_telemetry(monkeypatch, capsys):
    from runbooks_tpu.cli import main as cli

    client = FakeCluster()
    m = Model.new("m1", spec={"image": "x"})
    m.obj["status"] = {"telemetry": {"step": 7, "loss": 3.25,
                                     "goodput": 0.9}}
    client.create(m.obj)
    monkeypatch.setattr(cli, "make_client", lambda args: client)
    assert cli.main(["get", ""]) == 0
    out = capsys.readouterr().out
    assert "TELEMETRY" in out
    assert "step=7" in out and "loss=3.25" in out


# ---------------------------------------------------------------------------
# Metrics-catalog drift check (satellite)
# ---------------------------------------------------------------------------

_METRIC_NAME_RE = re.compile(r"`([a-z][a-z0-9_]*_[a-z0-9_]+)`")


def _doc_catalog_names():
    doc = os.path.join(os.path.dirname(__file__), "..", "docs",
                       "observability.md")
    with open(doc) as f:
        text = f.read()
    section = text.split("### Metric catalog", 1)[1].split("###", 1)[0]
    names = set()
    for line in section.splitlines():
        if not line.startswith("|") or "---" in line:
            continue
        # Only the first (Metric) column holds family names; label/unit
        # columns use single-word tokens that don't match the pattern.
        first_cell = line.split("|")[1]
        names.update(_METRIC_NAME_RE.findall(first_cell))
    return names


def test_metric_catalog_doc_in_sync_with_code():
    doc_names = _doc_catalog_names()
    code_names = set(CATALOG)
    assert doc_names - code_names == set(), \
        f"docs/observability.md lists unknown metrics: {doc_names - code_names}"
    assert code_names - doc_names == set(), \
        f"metrics missing from docs/observability.md: {code_names - doc_names}"


def test_runtime_families_are_cataloged(harness):
    """Every family the runtime paths actually register must be in the
    catalog (and therefore, by the test above, in the docs)."""
    client, ctx, mgr = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    reg = replica_registry()
    httpd = serve_metrics(0, reg)
    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry)
    make_pod(client, "srv-a", {"server": "srv"}, httpd.server_address[1])
    try:
        scraper.scrape_once()
    finally:
        httpd.shutdown()
        httpd.server_close()
    mgr.reconcile_until_stable()

    from runbooks_tpu.controller.metrics import REGISTRY as GLOBAL

    prefixes = ("controller_", "serve_", "train_", "fleet_", "process_")
    for text in (registry.render(), GLOBAL.render()):
        families = obs_metrics.parse_exposition(text)
        runtime = {n for n in families if n.startswith(prefixes)}
        assert runtime <= set(CATALOG), \
            f"uncataloged families registered at runtime: " \
            f"{runtime - set(CATALOG)}"


# ---------------------------------------------------------------------------
# Bench regression gate (satellite)
# ---------------------------------------------------------------------------

def test_bench_regression_gate():
    import bench

    baseline = json.load(open(os.path.join(
        os.path.dirname(__file__), "..",
        "BENCH_BASELINE.json")))["cpu_debug_step_time_s"]
    # Inside the gate: flagged clean.
    ok = bench.check_step_time_regression(baseline * 0.9, "cpu", "debug")
    assert ok["regression"] is False
    assert ok["baseline_step_time_s"] == baseline
    # Past the gate: flagged loudly (and strict mode would exit 3).
    bad = bench.check_step_time_regression(baseline * 2, "cpu", "debug")
    assert bad["regression"] is True
    assert bad["step_time_delta_pct"] == pytest.approx(100.0, abs=0.2)
    # Gate scope: only the default CPU debug shape.
    assert bench.check_step_time_regression(baseline * 2, "tpu",
                                            "debug") == {}
    assert bench.check_step_time_regression(baseline * 2, "cpu",
                                            "bench-410m") == {}


def test_bench_regression_gate_strict_exits(monkeypatch):
    import bench

    monkeypatch.setenv("RBT_BENCH_GATE_STRICT", "1")
    with pytest.raises(SystemExit):
        bench.check_step_time_regression(10.0, "cpu", "debug")
