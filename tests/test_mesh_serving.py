"""Sharded multi-chip serving mesh (docs/tensor-parallel-performance.md).

Parity discipline, as everywhere in the serving tests: the sharded
engine is an OPTIMIZATION, so a mesh_tensor=2 engine must be
token-for-token identical to the single-device engine on greedy
decode — dense, paged, speculative, and the multi-tenant LoRA pool.
The harness pins 8 virtual CPU devices (conftest) and exact matmul
precision, so parity is byte-exact: the mesh shards the SAME program
(GSPMD inserts the collectives; the math is unchanged).

Compile discipline rides along: a mesh engine's warmup must cover the
full program set so steady-state traffic under the mesh triggers ZERO
unexpected compiles (the census baseline carries *_sharded entries for
exactly these programs).
"""

import dataclasses
import os

import jax
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import init_params
from runbooks_tpu.serve.engine import InferenceEngine, Request
from runbooks_tpu.serve.paging import PagedInferenceEngine
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh


def tiny_cfg(**over):
    base = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                max_seq_len=64, dtype="float32")
    base.update(over)
    return dataclasses.replace(get_config("llama2-7b"), **base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.key(0))


@pytest.fixture(scope="module")
def mesh():
    """tensor=2 serving mesh over the 8-device CPU harness (fsdp soaks
    the rest, like a real single-host slice would)."""
    return make_mesh(MeshConfig(data=1, fsdp=4, tensor=2))


PROMPTS = [[5, 9, 17], [3, 4, 5, 6, 7], [40, 2], [8, 8, 8, 9]]
REP_PROMPT = [5, 6, 7, 8] * 5 + [5, 6]


def greedy_reqs(prompts, max_tokens=8, **kw):
    return [Request(prompt_tokens=list(p), max_tokens=max_tokens,
                    temperature=0.0, **kw) for p in prompts]


def outputs(engine, reqs):
    engine.generate(reqs)
    return [r.output_tokens for r in reqs]


# ---------------------------------------------------------------------------
# Greedy parity: single-device vs mesh_tensor=2
# ---------------------------------------------------------------------------

def test_mesh_parity_dense(model, mesh):
    cfg, params = model
    want = outputs(InferenceEngine(cfg, params, max_slots=2),
                   greedy_reqs(PROMPTS[:2]))
    got = outputs(InferenceEngine(cfg, params, max_slots=2, mesh=mesh),
                  greedy_reqs(PROMPTS[:2]))
    assert got == want
    # weights actually sharded: attention heads split over tensor
    eng = InferenceEngine(cfg, params, max_slots=2, mesh=mesh)
    wq = eng.params["layers"]["attn"]["wq"]
    assert "tensor" in jax.tree.leaves(wq)[0].sharding.spec


def test_mesh_parity_paged(model, mesh):
    cfg, params = model
    want = outputs(
        PagedInferenceEngine(cfg, params, max_slots=2, page_size=16),
        greedy_reqs(PROMPTS[:2]))
    eng = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                               mesh=mesh)
    got = outputs(eng, greedy_reqs(PROMPTS[:2]))
    assert got == want
    # the pool shards kv-heads over tensor; page tables stay host-side
    assert "tensor" in eng.cache.k.sharding.spec


def test_mesh_parity_paged_prefix_sharing(model, mesh):
    """Radix prefix hits splice SHARDED prefix pages into a sharded
    pool — the host-side page tables are oblivious to the mesh."""
    cfg, params = model
    shared = list(range(1, 33))
    prompts = [shared + [40 + i] for i in range(3)]

    def run(mesh_):
        eng = PagedInferenceEngine(cfg, params, max_slots=2,
                                   page_size=16, mesh=mesh_)
        eng.register_prefix(shared)
        return outputs(eng, greedy_reqs(prompts, max_tokens=5))

    assert run(mesh) == run(None)


def test_mesh_parity_speculative(model, mesh):
    cfg, params = model
    prompts = [REP_PROMPT, PROMPTS[1]]
    want = outputs(
        PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                             speculative="off"),
        greedy_reqs(prompts, max_tokens=12))
    on = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                              mesh=mesh, speculative="ngram",
                              draft_tokens=4)
    got = outputs(on, greedy_reqs(prompts, max_tokens=12))
    assert got == want
    # the [B, K+1] verify actually ran under the mesh
    assert on.spec_drafted > 0


def test_mesh_parity_lora_pool(model, mesh, tmp_path):
    """Four distinct tenants on ONE mesh-sharded pooled engine ==
    the single-device pooled engine, token for token (the adapter pool
    shards its lanes by the same logical axes as the base weights)."""
    from runbooks_tpu.serve.lora_pool import save_adapter
    from runbooks_tpu.train.lora import LoraConfig, init_lora

    cfg, params = model
    cfg = dataclasses.replace(cfg, adapter_pool=4, lora_rank=8)
    paths = []
    for i in range(4):
        lora = init_lora(params, LoraConfig(rank=4, alpha=8.0),
                         jax.random.key(10 + i))
        lora = jax.tree.map(
            lambda x, i=i: x + 0.03 * jax.random.normal(
                jax.random.key(20 + i), x.shape, x.dtype), lora)
        path = os.path.join(str(tmp_path), f"tenant{i}")
        save_adapter(path, lora, rank=4, alpha=8.0)
        paths.append(path)

    def reqs():
        return [Request(prompt_tokens=list(p), max_tokens=8,
                        temperature=0.0, adapter=a)
                for p, a in zip(PROMPTS, paths)]

    want = outputs(
        PagedInferenceEngine(cfg, params, max_slots=4, page_size=16),
        reqs())
    eng = PagedInferenceEngine(cfg, params, max_slots=4, page_size=16,
                               mesh=mesh)
    got = outputs(eng, reqs())
    assert got == want


def test_mesh_collective_matmul_auto(model, mesh):
    """collective_matmul: auto resolves ON under the serving mesh and
    still decodes to finished requests (ring reorders the float
    accumulation, so the oracle here is completion + output length,
    not byte parity — docs/tensor-parallel-performance.md)."""
    cfg, params = model
    cfg = dataclasses.replace(cfg, collective_matmul="auto")
    eng = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                               mesh=mesh)
    reqs = greedy_reqs(PROMPTS[:2], max_tokens=6)
    eng.generate(reqs)
    assert all(r.finished for r in reqs)
    assert all(len(r.output_tokens) == 6 for r in reqs)


# ---------------------------------------------------------------------------
# Compile discipline under the mesh
# ---------------------------------------------------------------------------

def test_mesh_zero_unexpected_compiles_in_steady_loop(model, mesh):
    from runbooks_tpu.obs import device as obs_device

    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=2,
                                  page_size=16, mesh=mesh)
    try:
        engine.warmup()
        sentinel = obs_device.SENTINEL
        before = sentinel.unexpected
        shared = list(range(1, 33))
        engine.register_prefix(shared)
        reqs = [Request(prompt_tokens=shared + [40 + i], max_tokens=5,
                        temperature=0.0) for i in range(3)]
        reqs += [Request(prompt_tokens=[9, 8, 7], max_tokens=5,
                         temperature=0.0)]
        for r in reqs:
            engine.submit(r)
        while engine.has_work():
            engine.step()
        assert all(r.finished for r in reqs)
        assert sentinel.unexpected == before, sentinel.recent_unexpected()
    finally:
        engine.release_steady()


def test_mesh_host_tier_swap_and_preemption_zero_compiles(model, mesh):
    """Host KV tier + QoS preemption under tensor=2: eviction demotes
    each chip's kv-head shard of the page to the host buffer, a
    returning match swaps it back in, and a batch slot preempts for an
    interactive queue head — token-for-token identical to the
    single-device engine, with zero unexpected compiles across the
    swap-out, swap-in, and preemption-resume paths
    (docs/paged-kv.md "Host tier and preemption")."""
    from runbooks_tpu.obs import device as obs_device

    cfg, params = model
    shared = list(range(1, 33))

    def run(mesh_):
        engine = PagedInferenceEngine(cfg, params, max_slots=1,
                                      page_size=16, num_pages=5,
                                      kv_host_pages=8, preemption="swap",
                                      decode_chunk=2, mesh=mesh_)
        engine.warmup()
        sentinel = obs_device.SENTINEL
        before = sentinel.unexpected
        try:
            engine.register_prefix(shared)
            # demote both (sharded) prefix pages to host RAM
            assert engine.pager.radix.evict(10 ** 6) == 2
            ret = Request(prompt_tokens=shared + [50], max_tokens=5,
                          temperature=0.0)
            engine.submit(ret)        # admission swaps the prefix back in
            while not ret.finished:
                engine.step()
            batch = Request(prompt_tokens=list(shared), max_tokens=16,
                            temperature=0.0, priority="batch")
            engine.submit(batch)
            for _ in range(3):        # admit + decode a few tokens
                engine.step()
            inter = Request(prompt_tokens=list(range(90, 106)),
                            max_tokens=8, temperature=0.0,
                            priority="interactive")
            engine.submit(inter)      # displaces the batch slot
            while engine.has_work():
                engine.step()
            assert engine.pager.radix.pages_swapped_out >= 2
            assert engine.pager.pages_swapped_in >= 2
            assert engine.preemptions == 1 == engine.preempted_resumed
            assert sentinel.unexpected == before, \
                sentinel.recent_unexpected()
            return [ret.output_tokens, batch.output_tokens,
                    inter.output_tokens]
        finally:
            engine.release_steady()

    assert run(mesh) == run(None)


# ---------------------------------------------------------------------------
# Per-device HBM accounting
# ---------------------------------------------------------------------------

def test_mesh_kv_occupancy_per_device_bytes(model, mesh):
    cfg, params = model
    plain = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16)
    occ = plain.kv_occupancy()
    # unsharded: per-device == aggregate
    assert occ["kv_pool_bytes_per_device"] == occ["kv_pool_bytes"]
    eng = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                               mesh=mesh)
    occ = eng.kv_occupancy()
    # tensor=2 halves each chip's share of the kv-head-sharded pool
    assert occ["kv_pool_bytes_per_device"] * 2 == occ["kv_pool_bytes"]
    assert occ["bytes_per_page_per_device"] * 2 == occ["bytes_per_page"]


# ---------------------------------------------------------------------------
# Mesh-geometry validation: precise, named constraints
# ---------------------------------------------------------------------------

def test_mesh_geometry_validation(model):
    cfg, params = model
    with pytest.raises(ValueError, match="jax.sharding.Mesh"):
        PagedInferenceEngine(cfg, params, max_slots=2, mesh=object())
    bad = make_mesh(MeshConfig(data=1, fsdp=1, tensor=8))
    with pytest.raises(ValueError,
                       match="kv-heads not divisible by mesh_tensor"):
        PagedInferenceEngine(cfg, params, max_slots=2, mesh=bad)


def test_controller_mesh_param_validation():
    from runbooks_tpu.controller.common import validate_params

    assert validate_params({"mesh_tensor": 4}) is None
    assert validate_params({"mesh_tensor": 2, "mesh_fsdp": -1}) is None
    assert "unknown mesh axis" in validate_params({"mesh_tensro": 2})
    assert "not an integer" in validate_params({"mesh_tensor": "two"})
    assert ">= 1" in validate_params({"mesh_tensor": 0})
    assert "at most one mesh axis" in validate_params(
        {"mesh_tensor": -1, "mesh_fsdp": -1})


def test_controller_server_mesh_geometry():
    from runbooks_tpu.api.types import Server
    from runbooks_tpu.controller.server import _validate_serve_mesh

    def srv(params, tpu=None):
        spec = {"params": params}
        if tpu:
            spec["resources"] = {"tpu": tpu}
        return Server({"kind": "Server",
                       "metadata": {"name": "s", "namespace": "d"},
                       "spec": spec})

    # pipeline stages are a training axis
    assert "mesh_stage" in _validate_serve_mesh(
        srv({"mesh_stage": 2}))
    # malformed tpu block surfaces as a condition, not a crash-loop
    assert "spec.resources.tpu" in _validate_serve_mesh(
        srv({"mesh_tensor": 2}, {"type": "v5p", "topology": "bogus"}))
    # mesh product must match the slice's chips
    assert "provides" in _validate_serve_mesh(
        srv({"mesh_tensor": 2},
            {"type": "v5p", "topology": "2x2x1"}))
    assert _validate_serve_mesh(
        srv({"mesh_tensor": 4},
            {"type": "v5p", "topology": "2x2x1"})) is None
    # -1 fill adapts to whatever the slice provides
    assert _validate_serve_mesh(
        srv({"mesh_tensor": 2, "mesh_fsdp": -1},
            {"type": "v5p", "topology": "2x2x1"})) is None
    # a mesh replica is one process: multi-host slices are out
    assert "hosts" in _validate_serve_mesh(
        srv({"mesh_tensor": 8}, {"type": "v5e", "topology": "4x4"}))
