"""Paged KV cache + radix-tree prefix sharing (serve/paging.py).

Correctness oracle, as for the dense engine: greedy rollout through the
full no-cache forward must equal the paged engine's cached decode — with
and without shared prefix pages, across page boundaries, under page
pressure, and mid-divergence of requests sharing pages copy-on-write.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.serve.engine import (
    EngineOverloaded,
    InferenceEngine,
    Request,
)
from runbooks_tpu.serve.paging import (
    PageAllocator,
    PagedInferenceEngine,
    RadixTree,
    page_bucket,
    paged_prefill_shapes,
    prefix_page_buckets,
    view_page_buckets_for,
)


def tiny_cfg(**over):
    base = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
                max_seq_len=64, dtype="float32")
    base.update(over)
    return dataclasses.replace(get_config("llama2-7b"), **base)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_cfg()
    return cfg, init_params(cfg, jax.random.key(0))


def greedy_rollout(cfg, params, prompt, n):
    toks = list(prompt)
    for _ in range(n):
        logits, _ = forward(cfg, params, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


# ---------------------------------------------------------------------------
# Allocator
# ---------------------------------------------------------------------------

def test_allocator_alloc_free_refcount_invariants():
    a = PageAllocator(4)
    assert (a.free_count, a.used_count) == (4, 0)
    pages = a.alloc(3)
    assert sorted(pages) == pages and len(set(pages)) == 3
    assert (a.free_count, a.used_count) == (1, 3)
    assert all(a.refcount(p) == 1 for p in pages)
    # all-or-nothing: an unsatisfiable request takes nothing
    assert a.alloc(2) is None
    assert a.free_count == 1
    a.incref(pages[:1])
    assert a.refcount(pages[0]) == 2
    # one decref does not free a shared page; the second does
    assert a.decref(pages[:1]) == []
    assert a.decref(pages[:1]) == [pages[0]]
    assert a.free_count == 2
    # freeing the rest returns everything
    a.decref(pages[1:])
    assert (a.free_count, a.used_count) == (4, 0)
    with pytest.raises(RuntimeError):
        a.decref([pages[0]])  # double-free is a bug, not a no-op
    with pytest.raises(RuntimeError):
        a.incref([pages[0]])  # incref of a free page likewise


# ---------------------------------------------------------------------------
# Radix tree
# ---------------------------------------------------------------------------

def test_radix_match_insert_partial_page_boundary():
    a = PageAllocator(8)
    t = RadixTree(4, a)
    toks = list(range(10))          # 2 full pages + a 2-token tail
    pages = a.alloc(3)              # page 2 holds the partial tail
    adopted = t.insert(toks, pages)
    # only COMPLETE pages enter the tree — the partial tail page never
    # becomes shareable (its tail garbage must not be attributed tokens)
    assert adopted == 2 and t.nodes == 2
    assert t.match(toks) == pages[:2]
    # a shorter query matches only whole pages it covers
    assert t.match(toks[:7]) == pages[:1]
    assert t.match(toks[:3]) == []
    # diverging second sequence shares page 0, adds its own page 1
    toks2 = toks[:4] + [99, 98, 97, 96]
    pages2 = a.alloc(2)
    assert t.insert(toks2, pages2) == 1          # page 0 already present
    assert t.match(toks2) == [pages[0], pages2[1]]
    # the duplicate page2[0] stays the caller's: tree never took a ref
    assert a.refcount(pages2[0]) == 1
    assert a.refcount(pages[0]) == 2             # caller + tree


def test_radix_evict_lru_and_refcount_pinning():
    a = PageAllocator(8)
    t = RadixTree(2, a)
    old = a.alloc(2)
    t.insert([1, 2, 3, 4], old)
    new = a.alloc(2)
    t.insert([5, 6, 7, 8], new)
    # callers drop their refs; tree-only pages are evictable
    a.decref(old)
    a.decref(new)
    t.match([5, 6, 7, 8])  # refresh: `new` is most recently used
    assert t.evict(1) == 1
    # LRU victim is the *leaf* of the old chain (depth-first from the
    # tail); its parent remains until a later round
    assert t.match([1, 2, 3, 4]) == old[:1]
    assert a.free_count == 5
    # a pinned page (live slot ref) is never evicted
    a.incref([new[0]])
    freed = t.evict(10)
    assert a.refcount(new[0]) == 2               # still tree + pin
    assert t.match([5, 6]) == [new[0]]
    # everything unpinned is gone (old chain fully cascaded)
    assert t.match([1, 2]) == []
    assert freed == 2                            # old[0] + new[1]
    assert t.pages_evicted == 3


def test_bucket_helpers():
    assert prefix_page_buckets(4) == [1, 2, 4]
    assert prefix_page_buckets(6) == [1, 2, 4, 6]
    assert [page_bucket(n, 4) for n in (0, 1, 2, 3, 4)] == [0, 1, 2, 4, 4]
    assert view_page_buckets_for(64, 16) == [4]
    shapes = paged_prefill_shapes([16, 32, 64], 4, 16, 64)
    # every reachable (suffix bucket, prefix-page bucket): ppb=4 (min 3
    # pages = 48 shared tokens) leaves at most a 16-token suffix
    assert (64, 4) not in shapes and (32, 4) not in shapes
    assert (16, 4) in shapes and (64, 1) in shapes
    assert len(shapes) == 9


# ---------------------------------------------------------------------------
# Engine parity vs the dense oracle
# ---------------------------------------------------------------------------

def test_paged_matches_dense_greedy(model):
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=4, page_size=16)
    prompts = [[5, 9, 17], list(range(3, 21)), [42]]
    reqs = [Request(prompt_tokens=p, max_tokens=8, temperature=0.0)
            for p in prompts]
    engine.generate(reqs)
    for p, r in zip(prompts, reqs):
        expect = greedy_rollout(cfg, params, p, 8)
        assert r.output_tokens == expect, (p, r.output_tokens, expect)
    # all pages released or adopted: nothing leaked to dead slots
    occ = engine.pager.occupancy()
    assert occ["pages_used"] == occ["pages_shared"]


def test_paged_matches_dense_greedy_bf16():
    cfg = tiny_cfg(dtype="bfloat16")
    params = init_params(cfg, jax.random.key(0))
    dense = InferenceEngine(cfg, params, max_slots=2)
    paged = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16)
    prompt = list(range(7, 27))
    rd = Request(prompt_tokens=prompt, max_tokens=8, temperature=0.0)
    rp = Request(prompt_tokens=prompt, max_tokens=8, temperature=0.0)
    dense.generate([rd])
    paged.generate([rp])
    assert rd.output_tokens == rp.output_tokens


def test_paged_int8_kv_matches_dense_int8(model):
    cfg, params = model
    dense = InferenceEngine(cfg, params, max_slots=2, quantize_kv=True)
    paged = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                                 quantize_kv=True)
    assert paged.cache.quantized
    prompt = [7, 3, 11, 2, 9, 40, 41]
    rd = Request(prompt_tokens=prompt, max_tokens=8, temperature=0.0)
    rp = Request(prompt_tokens=prompt, max_tokens=8, temperature=0.0)
    dense.generate([rd])
    paged.generate([rp])
    # identical quantize-at-write / dequantize-at-read path: exact match
    assert rd.output_tokens == rp.output_tokens


def test_shared_prefix_parity_and_page_accounting(model):
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=4, page_size=16)
    shared = list(range(1, 34))      # 33 tokens -> 2 full shared pages
    assert engine.register_prefix(shared) == 32
    assert engine.has_prefix(shared + [99])
    occ = engine.pager.occupancy()
    assert occ["pages_shared"] == 2
    r = Request(prompt_tokens=shared + [50, 51], max_tokens=6,
                temperature=0.0)
    engine.generate([r])
    assert r.output_tokens == greedy_rollout(cfg, params,
                                             shared + [50, 51], 6)
    # per-page reuse accounting: 2 physical pages mapped, 32 tokens not
    # re-prefilled, one admission-level hit
    assert engine.pager.pages_reused_total == 2
    assert engine.prefix_tokens_reused == 32
    assert (engine.prefix_hits, engine.prefix_lookups) == (1, 2)


def test_cow_divergence_mid_generation(model):
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=4, page_size=16)
    shared = list(range(1, 33))      # exactly 2 pages
    engine.register_prefix(shared)
    base = engine.pager.occupancy()["pages_shared"]
    # two CONCURRENT requests share the prefix pages and diverge from
    # the first private token; each must match its own oracle (a write
    # leaking into a shared page would corrupt the sibling)
    ra = Request(prompt_tokens=shared + [50], max_tokens=8,
                 temperature=0.0)
    rb = Request(prompt_tokens=shared + [60, 61], max_tokens=8,
                 temperature=0.0)
    engine.submit(ra)
    engine.submit(rb)
    while engine.has_work():
        engine.step()
    assert ra.output_tokens == greedy_rollout(cfg, params,
                                              shared + [50], 8)
    assert rb.output_tokens == greedy_rollout(cfg, params,
                                              shared + [60, 61], 8)
    assert engine.pager.pages_reused_total >= 4  # 2 pages x 2 requests
    # and the shared pages survived both generations intact: a THIRD
    # request over the same prefix still matches its oracle
    rc = Request(prompt_tokens=shared + [70], max_tokens=6,
                 temperature=0.0)
    engine.generate([rc])
    assert rc.output_tokens == greedy_rollout(cfg, params,
                                              shared + [70], 6)
    assert engine.pager.occupancy()["pages_shared"] >= base


def test_finished_requests_seed_the_radix_tree(model):
    """Many-user prefix reuse without any registration: request 1's
    prompt pages are adopted at finish; request 2 (same system prompt,
    different question) reuses them."""
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16)
    system = list(range(2, 20))      # 18 tokens -> 1 full page
    r1 = Request(prompt_tokens=system + [30], max_tokens=4,
                 temperature=0.0)
    engine.generate([r1])
    assert engine.pager.occupancy()["pages_shared"] >= 1
    assert engine.pager.pages_reused_total == 0
    r2 = Request(prompt_tokens=system + [31, 32], max_tokens=6,
                 temperature=0.0)
    engine.generate([r2])
    assert engine.pager.pages_reused_total >= 1
    assert r2.output_tokens == greedy_rollout(cfg, params,
                                              system + [31, 32], 6)


def test_multi_turn_adoption_extends_the_match(model):
    """Turn 2's prompt extends turn 1's prompt + reply: the pages written
    during generation (minus the never-written final token) are
    shareable, so the match deepens turn over turn — the paged
    generalization of the dense engine's auto_prefix."""
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16)
    prompt1 = list(range(1, 30))     # 29 tokens
    r1 = Request(prompt_tokens=prompt1, max_tokens=8, temperature=0.0)
    engine.generate([r1])
    # written extent = 29 + 8 - 1 = 36 -> 2 full pages adopted
    assert engine.pager.occupancy()["pages_shared"] == 2
    prompt2 = prompt1 + r1.output_tokens + [77]
    r2 = Request(prompt_tokens=prompt2, max_tokens=6, temperature=0.0)
    engine.generate([r2])
    assert engine.pager.pages_reused_total == 2
    assert r2.output_tokens == greedy_rollout(cfg, params, prompt2, 6)


def test_register_prefix_from_slot_is_noop_and_safe(model):
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16)
    assert engine.register_prefix_from_slot(0, [1, 2, 3]) == 0
    assert engine.prefix_warmup_shapes(32) == []
    assert engine.warm_prefix_shape((1,), 16, 1, None) is None


# ---------------------------------------------------------------------------
# Page pressure: backpressure, eviction, no corruption
# ---------------------------------------------------------------------------

def test_page_pressure_serializes_and_stays_correct(model):
    cfg, params = model
    # 4 slots but only enough pages for ONE max-reservation request at a
    # time: admission must serialize on pages, never corrupt
    engine = PagedInferenceEngine(cfg, params, max_slots=4, page_size=16,
                                  num_pages=4)
    prompts = [list(range(1, 33)), list(range(40, 72)),
               list(range(60, 92))]
    reqs = [Request(prompt_tokens=p, max_tokens=32, temperature=0.0)
            for p in prompts]    # reserve = 64 tokens = 4 pages each
    for r in reqs:
        engine.submit(r)
    engine.step()
    assert int(engine.active.sum()) == 1     # pages, not slots, gate
    assert len(engine.queue) == 2
    while engine.has_work():
        engine.step()
    for p, r in zip(prompts, reqs):
        expect = greedy_rollout(cfg, params, p,
                                len(r.output_tokens))
        assert r.output_tokens == expect


def test_page_exhaustion_backpressure_is_typed_overload(model):
    """The 429 path: a full pool backs the queue up; past max_queue,
    submit sheds with the same typed EngineOverloaded the HTTP layer
    maps to 429 + Retry-After — requests are never admitted into a pool
    that cannot hold them."""
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=4, page_size=16,
                                  num_pages=4, max_queue=2)
    mk = lambda i: Request(prompt_tokens=list(range(i, i + 32)),
                           max_tokens=32, temperature=0.0)
    engine.submit(mk(1))
    engine.step()                     # admitted: pool now full
    engine.submit(mk(2))
    engine.submit(mk(3))              # queue at its bound
    with pytest.raises(EngineOverloaded):
        engine.submit(mk(4))
    while engine.has_work():
        engine.step()


def test_eviction_makes_room_then_recomputes_evicted_prefix(model):
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                                  num_pages=5)
    shared = list(range(1, 33))
    engine.register_prefix(shared)    # 2 tree pages resident
    assert engine.pager.occupancy()["pages_shared"] == 2
    # a non-matching max-reservation request needs 4 pages -> evicts at
    # least one unreferenced prefix page
    big = Request(prompt_tokens=list(range(90, 122)), max_tokens=32,
                  temperature=0.0)
    engine.generate([big])
    assert engine.pager.radix.pages_evicted >= 1
    # the evicted prefix simply recomputes — correctness is unaffected
    r = Request(prompt_tokens=shared + [50], max_tokens=5,
                temperature=0.0)
    engine.generate([r])
    assert r.output_tokens == greedy_rollout(cfg, params, shared + [50],
                                             5)


def test_deadline_expiry_releases_pages(model):
    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16)
    r = Request(prompt_tokens=list(range(1, 20)), max_tokens=64,
                temperature=0.0, deadline_s=0.0)
    engine.submit(r)
    engine.step()
    # queued request expired before admission: empty-handed, zero pages
    assert r.finish_reason == "deadline"
    occ = engine.pager.occupancy()
    assert occ["pages_used"] == occ["pages_shared"]
    # active request expiring mid-generation frees its private pages too
    r2 = Request(prompt_tokens=list(range(1, 20)), max_tokens=64,
                 temperature=0.0, deadline_s=30.0)
    engine.submit(r2)
    engine.step()
    assert engine.active.any()
    r2.deadline_s = 0.0               # force expiry at the next step
    engine.step()
    assert r2.finish_reason == "deadline"
    occ = engine.pager.occupancy()
    assert occ["pages_used"] == occ["pages_shared"]


def test_geometry_validation(model):
    cfg, params = model
    with pytest.raises(ValueError, match="divide"):
        PagedInferenceEngine(cfg, params, max_slots=2, page_size=24)
    with pytest.raises(ValueError, match="one max-length"):
        PagedInferenceEngine(cfg, params, max_slots=2, page_size=16,
                             num_pages=2)
    with pytest.raises(ValueError, match="mesh"):
        PagedInferenceEngine(cfg, params, max_slots=2, mesh=object())


# ---------------------------------------------------------------------------
# Compile discipline
# ---------------------------------------------------------------------------

def test_zero_unexpected_compiles_in_paged_steady_loop(model):
    from runbooks_tpu.obs import device as obs_device

    cfg, params = model
    engine = PagedInferenceEngine(cfg, params, max_slots=2, page_size=16)
    try:
        engine.warmup()
        census = engine.warmup_census
        assert census["prefill_programs"] == 9 * 2  # shapes x rows
        assert census["decode_views"] == [4]
        sentinel = obs_device.SENTINEL
        before = sentinel.unexpected
        # steady traffic across every paged code path: plain admission,
        # radix-hit admission (several prefix-page buckets), batched
        # groups, decode, finish-adoption
        shared = list(range(1, 33))
        engine.register_prefix(shared)
        reqs = [Request(prompt_tokens=shared + [40 + i], max_tokens=5,
                        temperature=0.0) for i in range(3)]
        reqs += [Request(prompt_tokens=[9, 8, 7], max_tokens=5,
                         temperature=0.0)]
        for r in reqs:
            engine.submit(r)
        while engine.has_work():
            engine.step()
        assert all(r.finished for r in reqs)
        assert sentinel.unexpected == before, sentinel.recent_unexpected()
    finally:
        engine.release_steady()


# ---------------------------------------------------------------------------
# Serving surface: metrics, /debug/memory, rbt top, controller params
# ---------------------------------------------------------------------------

def test_http_paged_server_metrics_and_memory(model):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.serve.api import create_server

    cfg, params = model
    app = create_server(cfg, params, max_slots=2, kv_paging=True,
                        page_size=16)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.post("/v1/completions", json={
                "prompt": "hello paging", "max_tokens": 4,
                "temperature": 0.0})
            assert r.status == 200
            r = await client.get("/metrics")
            assert r.status == 200
            text = await r.text()
            for fam in ("serve_kv_pages_free", "serve_kv_pages_used",
                        "serve_kv_pages_shared",
                        "serve_prefix_pages_reused_total"):
                assert f"\n{fam} " in text or text.startswith(
                    f"{fam} "), fam
            r = await client.get("/debug/memory")
            assert r.status == 200
            body = await r.json()
            occ = body["kv_occupancy"]
            assert occ["paged"] and occ["page_size"] == 16
            # page-level byte attribution: shared (prefix_cache-like)
            # vs private bytes inside the one physical pool
            assert occ["kv_bytes_shared"] + occ["kv_bytes_private"] \
                == occ["pages_used"] * occ["bytes_per_page"]

    asyncio.run(drive())


def test_dense_metrics_do_not_export_page_series(model):
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from runbooks_tpu.obs import metrics as obs_metrics
    from runbooks_tpu.serve.api import create_server

    cfg, params = model
    # the process-wide registry may carry page series from a paged test
    # in this module — a fresh registry proves the DENSE path never sets
    # them (reset() is the test-only full wipe)
    obs_metrics.REGISTRY.reset()
    app = create_server(cfg, params, max_slots=2)

    async def drive():
        async with TestClient(TestServer(app)) as client:
            r = await client.get("/metrics")
            return await r.text()

    text = asyncio.run(drive())
    assert "serve_kv_pages_used" not in text
    assert "serve_kv_occupancy_ratio" in text


def test_rbt_top_slots_cell_paged_vs_dense():
    from runbooks_tpu.cli.main import _top_slots
    from runbooks_tpu.obs.metrics import parse_exposition

    paged = parse_exposition(
        "serve_active_slots 3\nserve_slots_total 8\n"
        "serve_kv_occupancy_ratio 0.5\n"
        "serve_kv_pages_free 48\nserve_kv_pages_used 16\n"
        "serve_kv_pages_shared 8\n")
    assert _top_slots(paged, {}) == "3/8 kv=25% shared=12%"
    dense = parse_exposition(
        "serve_active_slots 3\nserve_slots_total 8\n"
        "serve_kv_occupancy_ratio 0.5\n")
    assert _top_slots(dense, {}) == "3/8 kv=50%"


def test_validate_params_kv_paging():
    from runbooks_tpu.controller.common import validate_params

    assert validate_params({"kv_paging": "paged", "page_size": 16,
                            "num_pages": 512}) is None
    assert validate_params({"kvPaging": "off"}) is None
    assert "kv_paging" in validate_params({"kv_paging": "pagedd"})
    assert "page_size" in validate_params({"page_size": 0})
    assert "num_pages" in validate_params({"num_pages": "many"})
