"""Training fast-path tests (chunked fused CE, grad accumulation, prefetch).

- Chunked CE parity: values AND grads match the reference
  ``cross_entropy_loss`` path, with loss masks and packed segment_ids, and
  the [b, s, vocab] f32 logits tensor is provably absent from the chunked
  path's jaxpr (while provably present in the reference's — keeps the
  assertion honest).
- Accumulation equivalence: ``accumulate_steps=k`` over microbatches
  reproduces the single large-batch optimizer step (full fine-tune and
  LoRA, including composed with the chunked loss).
- Prefetcher: ordering, termination, close(), and exception propagation.
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.controller.common import validate_params
from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import forward, init_params
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh
from runbooks_tpu.train import data as data_mod
from runbooks_tpu.train.lora import (
    LoraConfig,
    create_lora_train_state,
    make_lora_train_step,
)
from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
from runbooks_tpu.train.step import (
    chunked_cross_entropy,
    create_train_state,
    cross_entropy_loss,
    make_train_step,
)


def tiny_cfg(**kw):
    # vocab_size deliberately distinct from every other dimension
    # (hidden 64, intermediate 128, seq <= 64) so the no-[b,s,v] jaxpr
    # detector below cannot be confounded by an MLP activation.
    return dataclasses.replace(
        get_config("llama2-7b"), vocab_size=160, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=4, num_kv_heads=2,
        head_dim=16, max_seq_len=64, dtype="float32", **kw,
    )


def packed_batch(cfg, batch=4, seq=20, seed=0):
    """Batch with a nontrivial loss mask and packed segment_ids/positions
    (two documents per row), like train/data.pack_documents emits."""
    rng = np.random.default_rng(seed)
    toks = rng.integers(1, cfg.vocab_size, (batch, seq + 1)).astype(np.int32)
    split = seq // 2
    seg = np.concatenate([np.full((batch, split), 1, np.int32),
                          np.full((batch, seq - split), 2, np.int32)], axis=1)
    pos = np.concatenate([np.arange(split), np.arange(seq - split)])
    pos = np.broadcast_to(pos, (batch, seq)).astype(np.int32)
    mask = (rng.random((batch, seq)) > 0.3).astype(np.float32)
    return {
        "tokens": jnp.asarray(toks[:, :-1]),
        "targets": jnp.asarray(toks[:, 1:]),
        "segment_ids": jnp.asarray(seg),
        "positions": jnp.asarray(pos),
        "loss_mask": jnp.asarray(mask),
    }


def reference_loss_fn(cfg, batch):
    def loss(params):
        logits, _ = forward(cfg, params, batch["tokens"],
                            positions=batch["positions"],
                            segment_ids=batch["segment_ids"])
        l, _ = cross_entropy_loss(logits, batch["targets"],
                                  batch["loss_mask"])
        return l
    return loss


def chunked_loss_fn(cfg, batch, chunk_size):
    def loss(params):
        acts, _ = forward(cfg, params, batch["tokens"],
                          positions=batch["positions"],
                          segment_ids=batch["segment_ids"],
                          return_activations=True)
        head = params["embed"].T if cfg.tie_embeddings else params["head"]
        l, _ = chunked_cross_entropy(acts, head, batch["targets"],
                                     batch["loss_mask"],
                                     chunk_size=chunk_size,
                                     compute_dtype=cfg.activation_dtype)
        return l
    return loss


# ---------------------------------------------------------------------------
# Chunked fused cross-entropy
# ---------------------------------------------------------------------------

def test_chunked_ce_parity_values_and_grads():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    # seq 20 with chunk 8 exercises the ragged-tail (padding) path.
    batch = packed_batch(cfg, seq=20)

    ref_l, ref_g = jax.value_and_grad(reference_loss_fn(cfg, batch))(params)
    chk_l, chk_g = jax.value_and_grad(
        chunked_loss_fn(cfg, batch, chunk_size=8))(params)

    np.testing.assert_allclose(chk_l, ref_l, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        chk_g, ref_g)


def test_chunked_ce_matches_with_uniform_weights_and_exact_chunks():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(1))
    batch = packed_batch(cfg, seq=16)
    batch["loss_mask"] = jnp.ones_like(batch["loss_mask"])

    ref = reference_loss_fn(cfg, batch)(params)
    chk = chunked_loss_fn(cfg, batch, chunk_size=4)(params)
    np.testing.assert_allclose(chk, ref, rtol=1e-5, atol=1e-6)


def _iter_avals(jaxpr):
    """All input/output avals in a jaxpr, recursing into sub-jaxprs
    (scan/checkpoint/pjit bodies)."""
    from jax.core import ClosedJaxpr, Jaxpr

    def subjaxprs(p):
        vals = p if isinstance(p, (tuple, list)) else (p,)
        for v in vals:
            if isinstance(v, ClosedJaxpr):
                yield v.jaxpr
            elif isinstance(v, Jaxpr):
                yield v

    for eqn in jaxpr.eqns:
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            if aval is not None and hasattr(aval, "shape"):
                yield aval
        for p in eqn.params.values():
            for sub in subjaxprs(p):
                yield from _iter_avals(sub)


def _has_full_logits(jaxpr, b, s, v):
    """Any f32 intermediate holding >= b*s*v elements with a vocab minor
    dim — the tensor the chunked path must never build (covers both
    [b, s, v] and scan-stacked [n, b, c, v] residuals)."""
    for aval in _iter_avals(jaxpr):
        if (np.prod(aval.shape or (1,)) >= b * s * v
                and aval.shape and aval.shape[-1] == v
                and aval.dtype == jnp.float32):
            return True
    return False


def test_chunked_ce_never_materializes_full_logits():
    cfg = tiny_cfg()
    params = init_params(cfg, jax.random.key(0))
    batch = packed_batch(cfg, seq=20)
    b, s = batch["tokens"].shape
    v = cfg.vocab_size

    ref_jaxpr = jax.make_jaxpr(
        jax.grad(reference_loss_fn(cfg, batch)))(params)
    chk_jaxpr = jax.make_jaxpr(
        jax.grad(chunked_loss_fn(cfg, batch, chunk_size=4)))(params)

    # The reference path DOES build [b, s, v] f32 logits (sanity: the
    # detector works), the chunked path never does — neither in the
    # forward nor as stacked scan residuals for the backward.
    assert _has_full_logits(ref_jaxpr.jaxpr, b, s, v)
    assert not _has_full_logits(chk_jaxpr.jaxpr, b, s, v)


def test_chunked_ce_direct_against_dense_reference():
    # Pure-op check, no transformer: random activations and head.
    rng = np.random.default_rng(3)
    b, s, d, v = 2, 13, 8, 33
    acts = jnp.asarray(rng.normal(size=(b, s, d)).astype(np.float32))
    head = jnp.asarray(rng.normal(size=(d, v)).astype(np.float32))
    targets = jnp.asarray(rng.integers(0, v, (b, s)).astype(np.int32))
    weights = jnp.asarray((rng.random((b, s)) > 0.5).astype(np.float32))

    logits = jnp.einsum("bsh,hv->bsv", acts, head,
                        preferred_element_type=jnp.float32)
    ref, ref_total = cross_entropy_loss(logits, targets, weights)
    # chunk 5 does not divide 13: padding path again, float32 compute.
    chk, chk_total = chunked_cross_entropy(
        acts, head, targets, weights, chunk_size=5,
        compute_dtype=jnp.float32)
    np.testing.assert_allclose(chk, ref, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(chk_total, ref_total)


# ---------------------------------------------------------------------------
# Gradient accumulation
# ---------------------------------------------------------------------------

def _stepped_params(cfg, mesh, batch, seed=0, **step_kw):
    opt = make_optimizer(OptimizerConfig(
        learning_rate=1e-3, warmup_steps=0, total_steps=100,
        schedule="constant"))
    state, shardings = create_train_state(cfg, opt, mesh,
                                          jax.random.key(seed))
    step = make_train_step(cfg, opt, mesh, shardings, **step_kw)
    with jax.set_mesh(mesh):
        state, metrics = step(state, batch)
    return state, metrics


def test_accumulation_matches_single_large_batch():
    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    batch = packed_batch(cfg, batch=8, seq=16)

    ref_state, ref_m = _stepped_params(cfg, mesh, batch)
    acc_state, acc_m = _stepped_params(cfg, mesh, batch,
                                       accumulate_steps=4)

    np.testing.assert_allclose(acc_m["loss"], ref_m["loss"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(acc_m["weight_tokens"],
                               ref_m["weight_tokens"])
    np.testing.assert_allclose(acc_m["grad_norm"], ref_m["grad_norm"],
                               rtol=1e-4, atol=1e-5)
    # adam's 1/(sqrt(nu)+eps) amplifies last-ulp grad reassociation on
    # near-zero entries; grads match to 1e-5, params to ~1e-4.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4),
        acc_state.params, ref_state.params)


def test_accumulation_composed_with_chunked_ce():
    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    batch = packed_batch(cfg, batch=8, seq=16, seed=7)

    ref_state, ref_m = _stepped_params(cfg, mesh, batch)
    acc_state, acc_m = _stepped_params(cfg, mesh, batch,
                                       accumulate_steps=2, loss_chunk=8)

    np.testing.assert_allclose(acc_m["loss"], ref_m["loss"],
                               rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        acc_state.params, ref_state.params)


def test_accumulation_matches_for_lora():
    from runbooks_tpu.models.transformer import param_logical_axes
    from runbooks_tpu.parallel.sharding import tree_shardings

    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    batch = packed_batch(cfg, batch=8, seq=16, seed=5)

    base = init_params(cfg, jax.random.key(0))
    base_sh = tree_shardings(jax.eval_shape(lambda: base),
                             param_logical_axes(cfg), mesh)
    base = jax.device_put(base, base_sh)
    lcfg = LoraConfig(rank=4)
    opt = make_optimizer(OptimizerConfig(
        learning_rate=1e-3, warmup_steps=0, total_steps=100,
        schedule="constant"))

    results = []
    for kw in ({}, {"accumulate_steps": 4, "loss_chunk": 8}):
        state, sh = create_lora_train_state(cfg, lcfg, base, opt, mesh,
                                            jax.random.key(1))
        step = make_lora_train_step(cfg, lcfg, opt, mesh, sh, base_sh, **kw)
        with jax.set_mesh(mesh):
            state, metrics = step(state, base, batch)
        results.append((state, metrics))

    (ref_state, ref_m), (acc_state, acc_m) = results
    np.testing.assert_allclose(acc_m["loss"], ref_m["loss"],
                               rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        acc_state.params, ref_state.params)


def test_accumulation_must_divide_batch():
    cfg = tiny_cfg()
    mesh = make_mesh(MeshConfig(data=2, fsdp=2, sequence=1, tensor=2))
    batch = packed_batch(cfg, batch=4, seq=16)
    with pytest.raises(ValueError, match="divide"):
        _stepped_params(cfg, mesh, batch, accumulate_steps=3)


def test_accumulation_rejected_under_1f1b():
    cfg = tiny_cfg(pipeline_schedule="1f1b")
    mesh = make_mesh(MeshConfig(stage=2, fsdp=4))
    opt = make_optimizer(OptimizerConfig())
    with pytest.raises(ValueError, match="1f1b"):
        make_train_step(cfg, opt, mesh, None, accumulate_steps=2)


# ---------------------------------------------------------------------------
# Controller validation of accumulate_steps
# ---------------------------------------------------------------------------

def test_validate_params_accumulate_steps():
    assert validate_params({"accumulate_steps": 4}) is None
    assert validate_params({"accumulateSteps": "8"}) is None
    assert validate_params({"accumulate_steps": 4, "batch_size": 32}) is None

    err = validate_params({"accumulate_steps": 3})
    assert err is not None and "accumulate_steps" in err
    err = validate_params({"accumulateSteps": "int8"})
    assert err is not None
    err = validate_params({"accumulate_steps": 4, "batch_size": 6})
    assert err is not None and "divide" in err
    # The env-lowercased spelling from_params honors is validated too.
    err = validate_params({"accumulatesteps": 3})
    assert err is not None
    # No batch_size in the spec: the trainer will use its default (8), so
    # an accum that does not divide 8 must still be caught here.
    err = validate_params({"accumulate_steps": 16})
    assert err is not None and "divide" in err
    assert validate_params({"accumulate_steps": 8}) is None

    # Integer params the trainer int()-coerces: a typo crash-loops the Job
    # without this.
    err = validate_params({"loss_chunk": "full"})
    assert err is not None and "integer" in err
    err = validate_params({"prefetch_depth": -1})
    assert err is not None
    assert validate_params({"loss_chunk": "512",
                            "prefetch_depth": 0}) is None

    # 1f1b pipeline (the default schedule) already microbatches:
    # accumulation there raises in make_train_step, so the controller must
    # reject it up front. gpipe overrides are fine.
    err = validate_params({"accumulate_steps": 2, "mesh_stage": 2,
                           "batch_size": 8})
    assert err is not None and "1f1b" in err
    assert validate_params({
        "accumulate_steps": 2, "mesh_stage": 2, "batch_size": 8,
        "model_overrides": {"pipeline_schedule": "gpipe"}}) is None


# ---------------------------------------------------------------------------
# Prefetcher
# ---------------------------------------------------------------------------

def test_prefetcher_preserves_order_and_terminates():
    src = [{"x": np.full((2,), i, np.int32)} for i in range(17)]
    with data_mod.Prefetcher(iter(src), depth=3) as pf:
        out = [int(b["x"][0]) for b in pf]
    assert out == list(range(17))


def test_prefetcher_applies_place_on_worker_thread():
    import threading

    main_tid = threading.get_ident()
    seen_tids = []

    def place(b):
        seen_tids.append(threading.get_ident())
        return {k: v * 2 for k, v in b.items()}

    src = [{"x": np.full((2,), i, np.int32)} for i in range(5)]
    with data_mod.Prefetcher(iter(src), depth=2, place=place) as pf:
        out = [int(b["x"][0]) for b in pf]
    assert out == [0, 2, 4, 6, 8]
    assert seen_tids and all(t != main_tid for t in seen_tids)


def test_prefetcher_close_midstream_joins_producer():
    def slow_gen():
        for i in range(1000):
            time.sleep(0.001)
            yield {"x": np.asarray([i])}

    pf = data_mod.Prefetcher(slow_gen(), depth=2)
    assert int(next(pf)["x"][0]) == 0
    pf.close()
    pf.close()  # idempotent
    assert not pf._thread.is_alive()
    with pytest.raises(StopIteration):
        next(pf)


def test_prefetcher_propagates_iterator_exception_in_order():
    def gen():
        yield {"x": np.asarray([0])}
        yield {"x": np.asarray([1])}
        raise RuntimeError("tokenizer exploded")

    pf = data_mod.Prefetcher(gen(), depth=4)
    assert int(next(pf)["x"][0]) == 0
    assert int(next(pf)["x"][0]) == 1
    with pytest.raises(RuntimeError, match="tokenizer exploded"):
        next(pf)
    pf.close()


def test_prefetcher_propagates_place_exception():
    def bad_place(b):
        raise ValueError("device_put failed")

    src = [{"x": np.asarray([1])}]
    pf = data_mod.Prefetcher(iter(src), depth=2, place=bad_place)
    with pytest.raises(ValueError, match="device_put failed"):
        next(pf)
    pf.close()


def test_device_placer_shards_batches_on_mesh(devices):
    mesh = make_mesh(MeshConfig(data=8, fsdp=1, sequence=1, tensor=1))
    place = data_mod.device_placer(mesh)
    batch = {"tokens": np.zeros((8, 16), np.int32),
             "loss_mask": np.ones((8, 16), np.float32)}
    placed = place(batch)
    toks = placed["tokens"]
    assert isinstance(toks, jax.Array) and toks.shape == (8, 16)
    assert len({s.device for s in toks.addressable_shards}) == 8


# ---------------------------------------------------------------------------
# Compilation cache helper
# ---------------------------------------------------------------------------

def test_enable_compilation_cache(tmp_path, monkeypatch):
    from runbooks_tpu.utils.jax_cache import enable_compilation_cache

    # CPU backend (this suite) is opt-in only: warm-cache reads corrupt
    # the heap on older CPU jaxlib (see utils/jax_cache.py docstring).
    target = str(tmp_path / "jax_cache")
    assert enable_compilation_cache(target) is None

    monkeypatch.setenv("RBT_JAX_CACHE", "1")  # force (the TPU default path)
    before = jax.config.jax_compilation_cache_dir
    try:
        assert enable_compilation_cache(target) == target
        assert jax.config.jax_compilation_cache_dir == target
    finally:
        # Restore so later tests in this process never hit a warm read.
        jax.config.update("jax_compilation_cache_dir", before)

    monkeypatch.setenv("RBT_JAX_CACHE", "0")
    assert enable_compilation_cache(str(tmp_path / "other")) is None
