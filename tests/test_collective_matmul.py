"""Overlapped collective-matmul tests (ops/collective_matmul.py).

Oracle strategy: the GSPMD path (collective_matmul="off") is the reference
— every ring result (primitive values, full-model logits, train-step loss
and grads, cached prefill/decode, quantized serving weights, LoRA,
accumulation) must match it to float tolerance on 2- and 4-way tensor
meshes carved from the 8 virtual CPU devices. Jaxpr evidence proves the
ring actually formed: ppermute present in the ring jaxprs (with exact
counts for the primitives), absent from the GSPMD jaxpr, and no psum
(all-reduce) after the row-parallel partial dots.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from runbooks_tpu.controller.common import validate_params
from runbooks_tpu.models.config import get_config
from runbooks_tpu.models.transformer import (
    KVCache,
    forward,
    init_params,
    resolve_collective_matmul,
)
from runbooks_tpu.ops.collective_matmul import (
    matmul_reduce_scatter,
    ring_ag_matmul,
    ring_supported,
)
from runbooks_tpu.ops.quantization import quantize, quantized_matmul
from runbooks_tpu.parallel.mesh import MeshConfig, make_mesh

TP2_MESH = dict(data=2, fsdp=2, tensor=2)
TP4_MESH = dict(data=2, fsdp=1, tensor=4)


def cm_cfg(**over):
    # debug is GQA (4 q heads over 2 kv heads); f32 for exact-math CPU
    # comparisons against the GSPMD oracle.
    kw = dict(dtype="float32")
    kw.update(over)
    return get_config("debug", **kw)


def toks(cfg, b=4, s=16, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)


# ---------------------------------------------------------------------------
# Primitives
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh_shape", [TP2_MESH, TP4_MESH],
                         ids=["tp2", "tp4"])
@pytest.mark.parametrize("bidirectional", [False, True], ids=["uni", "bidir"])
def test_primitive_values_match_matmul(mesh_shape, bidirectional):
    mesh = make_mesh(MeshConfig(**mesh_shape))
    x = jax.random.normal(jax.random.key(0), (4, 8, 64), jnp.float32)
    w_col = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    w_row = jax.random.normal(jax.random.key(2), (64, 64), jnp.float32)
    assert ring_supported("ag", x.shape, w_col, mesh)
    assert ring_supported("rs", x.shape, w_row, mesh)
    with jax.set_mesh(mesh):
        y = jax.jit(lambda x, w: ring_ag_matmul(
            x, w, mesh=mesh, compute_dtype=jnp.float32,
            bidirectional=bidirectional))(x, w_col)
        z = jax.jit(lambda x, w: matmul_reduce_scatter(
            x, w, mesh=mesh, compute_dtype=jnp.float32,
            bidirectional=bidirectional))(x, w_row)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x @ w_col),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(z), np.asarray(x @ w_row),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("mesh_shape", [TP2_MESH, TP4_MESH],
                         ids=["tp2", "tp4"])
def test_primitive_grads_match_matmul(mesh_shape):
    """The custom VJPs (AG bwd = matmul-RS ring + re-circulated dw ring;
    RS bwd = AG ring) must reproduce plain-autodiff gradients."""
    mesh = make_mesh(MeshConfig(**mesh_shape))
    x = jax.random.normal(jax.random.key(0), (4, 8, 64), jnp.float32)
    w_col = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    w_row = jax.random.normal(jax.random.key(2), (64, 64), jnp.float32)

    with jax.set_mesh(mesh):
        gx, gw = jax.jit(jax.grad(
            lambda x, w: jnp.sum(ring_ag_matmul(
                x, w, mesh=mesh, compute_dtype=jnp.float32) ** 2),
            argnums=(0, 1)))(x, w_col)
        hx, hw = jax.jit(jax.grad(
            lambda x, w: jnp.sum(matmul_reduce_scatter(
                x, w, mesh=mesh, compute_dtype=jnp.float32) ** 2),
            argnums=(0, 1)))(x, w_row)
    gx_r, gw_r = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2),
                          argnums=(0, 1))(x, w_col)
    hx_r, hw_r = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2),
                          argnums=(0, 1))(x, w_row)
    for got, want in ((gx, gx_r), (gw, gw_r), (hx, hx_r), (hw, hw_r)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("bits", [8, 4], ids=["int8", "int4"])
def test_primitive_quantized_matches_quantized_matmul(bits):
    """Dequant-fused ring == the fused quantized_matmul reference, both
    primitives, both packings (block 16 keeps tp=4 chunks block-aligned)."""
    mesh = make_mesh(MeshConfig(**TP4_MESH))
    x = jax.random.normal(jax.random.key(0), (4, 8, 64), jnp.float32)
    w_col = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    w_row = jax.random.normal(jax.random.key(2), (64, 64), jnp.float32)
    qa_col = quantize(w_col, bits=bits, block_size=16)
    qa_row = quantize(w_row, bits=bits, block_size=16)
    assert ring_supported("ag", x.shape, qa_col, mesh)
    assert ring_supported("rs", x.shape, qa_row, mesh)
    with jax.set_mesh(mesh):
        y = jax.jit(lambda x: ring_ag_matmul(
            x, qa_col, mesh=mesh, compute_dtype=jnp.float32))(x)
        z = jax.jit(lambda x: matmul_reduce_scatter(
            x, qa_row, mesh=mesh, compute_dtype=jnp.float32))(x)
    np.testing.assert_allclose(
        np.asarray(y),
        np.asarray(quantized_matmul(x, qa_col, compute_dtype=jnp.float32)),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(z),
        np.asarray(quantized_matmul(x, qa_row, compute_dtype=jnp.float32)),
        rtol=1e-5, atol=1e-5)


def test_primitive_jaxpr_ring_evidence():
    """tp-1 ppermutes per unidirectional ring, zero psums: the collective
    really is decomposed, not re-formed as a blocking all-reduce."""
    mesh = make_mesh(MeshConfig(**TP4_MESH))
    x = jax.random.normal(jax.random.key(0), (4, 8, 64), jnp.float32)
    w_col = jax.random.normal(jax.random.key(1), (64, 32), jnp.float32)
    w_row = jax.random.normal(jax.random.key(2), (64, 64), jnp.float32)
    with jax.set_mesh(mesh):
        ag_txt = str(jax.make_jaxpr(lambda x, w: ring_ag_matmul(
            x, w, mesh=mesh, bidirectional=False))(x, w_col))
        rs_txt = str(jax.make_jaxpr(lambda x, w: matmul_reduce_scatter(
            x, w, mesh=mesh, bidirectional=False))(x, w_row))
    assert ag_txt.count("ppermute") == 3  # tp-1 hops
    assert rs_txt.count("ppermute") == 3
    assert "psum" not in ag_txt
    assert "psum" not in rs_txt


def test_ring_supported_gating():
    mesh = make_mesh(MeshConfig(**TP2_MESH))
    no_tp = make_mesh(MeshConfig(data=2, fsdp=4))
    w = jnp.zeros((64, 32), jnp.float32)
    assert ring_supported("ag", (4, 8, 64), w, mesh)
    assert not ring_supported("ag", (4, 8, 64), w, no_tp)   # no tensor axis
    assert not ring_supported("ag", (4, 8, 63), w, mesh)    # contraction mismatch
    assert not ring_supported("ag", (4, 8, 65), jnp.zeros((65, 32)), mesh)
    assert not ring_supported("rs", (4, 8, 64), jnp.zeros((64, 33)), mesh)
    # Quantized: chunks must hold whole blocks.
    qa = quantize(jnp.ones((64, 32)), bits=8, block_size=64)
    assert not ring_supported("ag", (4, 8, 64), qa, mesh)   # 32-row chunk < block
    qa16 = quantize(jnp.ones((64, 32)), bits=8, block_size=16)
    assert ring_supported("ag", (4, 8, 64), qa16, mesh)


# ---------------------------------------------------------------------------
# Full model: logits / cache / jaxpr
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mesh_shape", [TP2_MESH, TP4_MESH],
                         ids=["tp2", "tp4"])
def test_forward_logits_match_gspmd(mesh_shape):
    cfg = cm_cfg()
    ring = dataclasses.replace(cfg, collective_matmul="ring")
    params = init_params(cfg, jax.random.key(0))
    tokens = toks(cfg)
    mesh = make_mesh(MeshConfig(**mesh_shape))
    with jax.set_mesh(mesh):
        want, _ = jax.jit(lambda p, t: forward(cfg, p, t))(params, tokens)
        got, _ = jax.jit(lambda p, t: forward(ring, p, t))(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_forward_jaxpr_has_ring_gspmd_does_not():
    cfg = cm_cfg()
    ring = dataclasses.replace(cfg, collective_matmul="ring")
    params = init_params(cfg, jax.random.key(0))
    tokens = toks(cfg)
    mesh = make_mesh(MeshConfig(**TP2_MESH))
    with jax.set_mesh(mesh):
        ring_txt = str(jax.make_jaxpr(
            lambda p, t: forward(ring, p, t))(params, tokens))
        off_txt = str(jax.make_jaxpr(
            lambda p, t: forward(cfg, p, t))(params, tokens))
    # 5 column-parallel rings (wq/wk/wv/wi_gate/wi_up) + 2 row-parallel
    # (attn wo, mlp wo), one ppermute each at tp=2, inside the scanned
    # layer body.
    assert ring_txt.count("ppermute") == 7
    assert off_txt.count("ppermute") == 0


def test_cached_prefill_decode_match_gspmd():
    """The serve engine's two program shapes — chunked prefill into a cache
    and single-token decode — through the ring path."""
    cfg = cm_cfg()
    ring = dataclasses.replace(cfg, collective_matmul="ring")
    params = init_params(cfg, jax.random.key(0))
    tokens = toks(cfg)
    mesh = make_mesh(MeshConfig(**TP2_MESH))

    def run(c):
        cache = KVCache.create(c, 4, 32)
        l1, cache = forward(c, params, tokens[:, :8], cache=cache)
        l2, cache = forward(c, params, tokens[:, 8:9], cache=cache)
        return l1, l2

    with jax.set_mesh(mesh):
        w1, w2 = jax.jit(lambda: run(cfg))()
        g1, g2 = jax.jit(lambda: run(ring))()
    np.testing.assert_allclose(np.asarray(g1), np.asarray(w1),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(g2), np.asarray(w2),
                               rtol=1e-4, atol=1e-4)


def test_forward_quantized_weights_match_gspmd():
    """int8/int4 serving weights through the ring (block 32 divides the
    h/tp = 64-row chunks of the debug shapes at tp=2)."""
    cfg = cm_cfg()
    ring = dataclasses.replace(cfg, collective_matmul="ring")
    params = init_params(cfg, jax.random.key(0))
    tokens = toks(cfg)
    mesh = make_mesh(MeshConfig(**TP2_MESH))
    for bits, mode in ((8, "int8"), (4, "int4")):
        from runbooks_tpu.ops.quantization import quantize_params

        qparams = quantize_params(
            jax.tree.map(lambda a: a, params), mode, block_size=32)
        with jax.set_mesh(mesh):
            want, _ = jax.jit(
                lambda p, t: forward(cfg, p, t))(qparams, tokens)
            got, _ = jax.jit(
                lambda p, t: forward(ring, p, t))(qparams, tokens)
            ring_txt = str(jax.make_jaxpr(
                lambda p, t: forward(ring, p, t))(qparams, tokens))
        assert ring_txt.count("ppermute") == 7, mode
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-4)


def test_resolve_gating():
    cfg = cm_cfg(collective_matmul="auto")
    # No mesh: off.
    assert resolve_collective_matmul(cfg) is False
    # tensor axis present: on.
    with jax.set_mesh(make_mesh(MeshConfig(**TP2_MESH))):
        assert resolve_collective_matmul(cfg) is True
        assert resolve_collective_matmul(
            dataclasses.replace(cfg, collective_matmul="off")) is False
    # No tensor axis: off.
    with jax.set_mesh(make_mesh(MeshConfig(data=2, fsdp=4))):
        assert resolve_collective_matmul(cfg) is False
    # Pipeline meshes keep GSPMD TP (stage-manual nesting unsupported).
    with jax.set_mesh(make_mesh(MeshConfig(stage=2, fsdp=2, tensor=2))):
        assert resolve_collective_matmul(cfg) is False
    with pytest.raises(ValueError, match="collective_matmul"):
        resolve_collective_matmul(
            dataclasses.replace(cfg, collective_matmul="rings"))


# ---------------------------------------------------------------------------
# Train step / LoRA / accumulation composition
# ---------------------------------------------------------------------------

def _train_setup(cfg, mesh, **step_kw):
    from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer
    from runbooks_tpu.train.step import create_train_state, make_train_step

    opt = make_optimizer(OptimizerConfig(total_steps=8, warmup_steps=0))
    state, shardings = create_train_state(cfg, opt, mesh, jax.random.key(0))
    step = make_train_step(cfg, opt, mesh, shardings, **step_kw)
    return state, step


def _batch(cfg, b=8, s=16, seed=3):
    t = np.asarray(toks(cfg, b=b, s=s + 1, seed=seed))
    return {"tokens": t[:, :-1], "targets": t[:, 1:],
            "loss_mask": np.ones((b, s), np.float32)}


@pytest.mark.parametrize("step_kw", [
    dict(),
    dict(accumulate_steps=2),
    dict(accumulate_steps=2, loss_chunk=8),
], ids=["plain", "accum2", "accum2-chunked-ce"])
def test_train_step_matches_gspmd(step_kw):
    """Loss and grad_norm over two optimizer steps, ring vs GSPMD — with
    gradient accumulation and the chunked fused CE composed on top."""
    cfg = cm_cfg()
    ring = dataclasses.replace(cfg, collective_matmul="ring")
    mesh = make_mesh(MeshConfig(**TP2_MESH))
    batch = _batch(cfg)

    results = {}
    for name, c in (("off", cfg), ("ring", ring)):
        state, step = _train_setup(c, mesh, **step_kw)
        metrics_seen = []
        with jax.set_mesh(mesh):
            for _ in range(2):
                state, metrics = step(state, batch)
                metrics_seen.append((float(metrics["loss"]),
                                    float(metrics["grad_norm"])))
        results[name] = metrics_seen
    for (lo, go), (lr, gr) in zip(results["off"], results["ring"]):
        np.testing.assert_allclose(lr, lo, rtol=1e-5)
        np.testing.assert_allclose(gr, go, rtol=1e-4)


def test_lora_train_step_matches_gspmd():
    """LoRA merges deltas into the base weights inside the differentiated
    graph; the ring custom-VJP must carry grads back through the merge to
    A/B identically to GSPMD."""
    from runbooks_tpu.train.lora import (
        LoraConfig,
        create_lora_train_state,
        make_lora_train_step,
    )
    from runbooks_tpu.models.transformer import param_logical_axes
    from runbooks_tpu.parallel.sharding import tree_shardings
    from runbooks_tpu.train.optimizer import OptimizerConfig, make_optimizer

    cfg = cm_cfg()
    ring = dataclasses.replace(cfg, collective_matmul="ring")
    mesh = make_mesh(MeshConfig(**TP2_MESH))
    lora_cfg = LoraConfig(rank=4)
    base = init_params(cfg, jax.random.key(0))
    base_shardings = tree_shardings(
        jax.eval_shape(lambda: base), param_logical_axes(cfg), mesh)
    base = jax.device_put(base, base_shardings)
    batch = _batch(cfg)
    opt = make_optimizer(OptimizerConfig(total_steps=8, warmup_steps=0))

    results = {}
    for name, c in (("off", cfg), ("ring", ring)):
        state, shardings = create_lora_train_state(
            c, lora_cfg, base, opt, mesh, jax.random.key(1))
        step = make_lora_train_step(c, lora_cfg, opt, mesh, shardings,
                                    base_shardings)
        with jax.set_mesh(mesh):
            state, metrics = step(state, base, batch)
            results[name] = (float(metrics["loss"]),
                             float(metrics["grad_norm"]))
    np.testing.assert_allclose(results["ring"][0], results["off"][0],
                               rtol=1e-5)
    np.testing.assert_allclose(results["ring"][1], results["off"][1],
                               rtol=1e-4)


# ---------------------------------------------------------------------------
# Controller / serve contract surface
# ---------------------------------------------------------------------------

def test_validate_params_collective_matmul():
    for key in ("collective_matmul", "collectiveMatmul", "collectivematmul"):
        for val in ("off", "ring", "auto"):
            assert validate_params({key: val}) is None, (key, val)
        err = validate_params({key: "rings"})
        assert err is not None and key in err
    assert validate_params({"collective_matmul": "on"}) is not None
    assert validate_params({"collective_matmul": 1}) is not None


def test_trainer_config_aliases_and_validation():
    from runbooks_tpu.train.trainer import TrainJobConfig, run_training

    job = TrainJobConfig.from_params({"collectiveMatmul": "ring"})
    assert job.collective_matmul == "ring"
    job = TrainJobConfig.from_params({"collectivematmul": "auto"})
    assert job.collective_matmul == "auto"
    with pytest.raises(ValueError, match="collective_matmul"):
        run_training(TrainJobConfig(collective_matmul="rings", steps=1))


def test_serve_load_model_rejects_bad_spelling(tmp_path):
    from runbooks_tpu.serve.api import load_model

    with pytest.raises(ValueError, match="collective_matmul"):
        load_model({"model": "debug", "checkpoint": str(tmp_path),
                    "collective_matmul": "rings"})
    cfg, _ = load_model({"model": "debug", "checkpoint": str(tmp_path),
                         "collective_matmul": "auto"})
    assert cfg.collective_matmul == "auto"
    # The controller validates the camelCase spec spelling for serve specs
    # too — a validated spec must not silently serve without the ring.
    cfg, _ = load_model({"model": "debug", "checkpoint": str(tmp_path),
                         "collectiveMatmul": "ring"})
    assert cfg.collective_matmul == "ring"


def test_engine_serves_with_ring_and_logs_census(capsys):
    """End-to-end serve smoke on a TP mesh with the ring path on: warmup
    (census line), batched prefill, chunked decode. Numerical parity of the
    underlying programs is covered by the forward/cache tests."""
    from runbooks_tpu.serve.engine import InferenceEngine, Request

    cfg = cm_cfg(collective_matmul="ring")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(MeshConfig(**TP2_MESH))
    eng = InferenceEngine(cfg, params, max_slots=2, max_seq_len=64,
                          mesh=mesh, decode_chunk=2)
    eng.warmup()
    census = [l for l in capsys.readouterr().out.splitlines()
              if "warmup census" in l]
    assert len(census) == 1 and "prefill programs" in census[0]
    reqs = [Request(prompt_tokens=[1, 2, 3, 4], max_tokens=8),
            Request(prompt_tokens=[5, 6, 7], max_tokens=8)]
    eng.generate(reqs, timeout_s=300)
    assert all(r.finished and len(r.output_tokens) == 8 for r in reqs)
