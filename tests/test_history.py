"""Fleet history + burn-rate SLO + `rbt dash` tests (ISSUE 13).

Covers: the obs/history.py rings (append/rollup/retention, staleness on
replica churn, window quantiles/increases with counter resets);
deterministic multi-window burn-rate transitions through the real
Server reconciler (fast-window onset with a window-named reason,
slow-window persistence after the fast window clears, shed on
recovery); snapshot persistence (restart restores history without
re-firing a debounced onset; corrupt snapshots cold-start loudly;
atomic writes); the controller's GET /metrics/history endpoint (bounded
parseable JSON for every mirrored family); `rbt dash` end to end
against a real scrape loop + fake replica expositions; the scraper's
self-observability satellites; the `rbt get` budget cell; and the
autoscaler's windowed p90.
"""

import dataclasses
import json
import os
import threading
import time
import urllib.request

import pytest

from runbooks_tpu.api import conditions as cond
from runbooks_tpu.api.types import API_VERSION, Model, Server
from runbooks_tpu.cloud.base import CommonConfig
from runbooks_tpu.cloud.local import LocalCloud
from runbooks_tpu.controller import burnrate
from runbooks_tpu.controller import fleet as fl
from runbooks_tpu.controller.manager import Ctx, Manager
from runbooks_tpu.controller.model import ModelReconciler
from runbooks_tpu.controller.server import ServerReconciler
from runbooks_tpu.k8s import objects as ko
from runbooks_tpu.k8s.fake import FakeCluster
from runbooks_tpu.obs import history as obs_history
from runbooks_tpu.obs import metrics as obs_metrics
from runbooks_tpu.obs.history import FleetHistory
from runbooks_tpu.obs.metrics import Registry, serve_metrics
from runbooks_tpu.sci.base import FakeSCI
from tests.test_fleet import make_pod, replica_registry, ttft_sample

SEL = {"kind": "Server", "namespace": "default", "name": "srv"}
BOUNDS = list(obs_metrics.DEFAULT_BUCKETS)
GOOD_I = BOUNDS.index(0.05)   # well under a 100 ms target
BAD_I = BOUNDS.index(0.25)    # over a 100 ms target


@pytest.fixture()
def harness(tmp_path):
    client = FakeCluster()
    cloud = LocalCloud(CommonConfig(
        cluster_name="testcluster",
        artifact_bucket_url=f"file://{tmp_path}/bucket",
        registry_url="registry.local:5000"))
    ctx = Ctx(client=client, cloud=cloud, sci=FakeSCI())
    mgr = Manager(ctx, [ModelReconciler(), ServerReconciler()])
    return client, ctx, mgr


@pytest.fixture(autouse=True)
def clean_fleet():
    fl.FLEET.reset()
    yield
    fl.FLEET.reset()


class LatencyFeeder:
    """Appends cumulative TTFT histogram snapshots: `per_step`
    observations per step, `bad_frac` of them above a 100 ms target."""

    def __init__(self, history, labels=None, name="serve_ttft_seconds"):
        self.h = history
        self.labels = labels or {**SEL, "replica": "p0"}
        self.name = name
        self.good = 0.0
        self.bad = 0.0

    def snapshot_at(self, t):
        cum, acc = [], 0.0
        for j in range(len(BOUNDS)):
            if j == GOOD_I:
                acc += self.good
            if j == BAD_I:
                acc += self.bad
            cum.append(acc)
        total = self.good + self.bad
        self.h.append_histogram(self.name, self.labels, t, BOUNDS, cum,
                                total, self.good * 0.05 + self.bad * 0.25)

    def feed(self, t_start, t_end, step_s, bad_frac, per_step=100):
        t = t_start
        while t <= t_end + 1e-9:
            self.good += per_step * (1.0 - bad_frac)
            self.bad += per_step * bad_frac
            self.snapshot_at(t)
            t += step_s
        return t - step_s


def ready_slo_server(client, mgr, slo):
    client.create(Model.new("m", spec={"image": "loader"}).obj)
    client.create(Server.new("srv", spec={
        "image": "img", "model": {"name": "m"}, "slo": slo}).obj)
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "m-modeller")
    mgr.reconcile_until_stable()


def fresh_sample(replica="srv-pod", ttft_s=0.01):
    """An up replica sample with HEALTHY instant telemetry and a fresh
    scrape age, so the instant fallback and staleness guards never fire
    on their own."""
    return dataclasses.replace(ttft_sample(replica, ttft_s),
                               last_success=time.monotonic())


def reconcile_srv(client, mgr):
    mgr.process_event("Server",
                      client.get(API_VERSION, "Server", "default", "srv"))
    return client.get(API_VERSION, "Server", "default", "srv")


# ---------------------------------------------------------------------------
# Ring mechanics
# ---------------------------------------------------------------------------

def test_ring_append_rollup_and_retention():
    h = FleetHistory(raw_step_s=1, raw_retention_s=10, rollup_step_s=5,
                     rollup_retention_s=100)
    t0 = 1000.0
    for i in range(40):
        h.append_scalar("g", {"replica": "p0"}, t0 + i, float(i))
    s = next(iter(h._series.values()))
    # Raw bounded by retention/step (+slack); rollup ~one point per 5 s.
    assert len(s.raw) <= 13
    assert s.raw[-1] == (t0 + 39, 39.0)
    assert len(s.rollup) == 8  # t0, then every 5 s boundary
    assert [p[0] - t0 for p in s.rollup][:3] == [0.0, 5.0, 10.0]
    stats = h.stats()
    assert stats["series"] == 1 and stats["points"] > 10


def test_window_quantile_exact_bucket_delta():
    h = FleetHistory(raw_step_s=10, raw_retention_s=900)
    feeder = LatencyFeeder(h)
    now = time.time()
    # 10 min of all-good traffic, then 5 min of all-bad.
    feeder.feed(now - 900, now - 301, 10, bad_frac=0.0)
    feeder.feed(now - 300, now, 10, bad_frac=1.0)
    # The 5 m window sees ONLY the bad phase: p50 lands in the 0.25
    # bucket, despite the cumulative distribution being half good.
    q = h.window_quantile("serve_ttft_seconds", 0.5, 300.0, now=now,
                          sel=SEL)
    assert 0.1 < q <= 0.25
    # The 15 m window mixes both: p50 back in the good bucket.
    q_all = h.window_quantile("serve_ttft_seconds", 0.5, 880.0, now=now,
                              sel=SEL)
    assert q_all <= 0.05


def test_window_increase_handles_counter_reset():
    h = FleetHistory(raw_step_s=1, raw_retention_s=300)
    now = time.time()
    labels = {**SEL, "replica": "p0"}
    for i, v in enumerate((100.0, 150.0, 200.0)):
        h.append_scalar("serve_requests_total", labels, now - 30 + i * 10,
                        v, "counter")
    assert h.window_increase("serve_requests_total", 25.0, now=now,
                             sel=SEL) == pytest.approx(100.0)
    # Replica restart: counter falls to 5 — the increase is the
    # post-reset value, not a negative.
    h.append_scalar("serve_requests_total", labels, now, 5.0, "counter")
    assert h.window_increase("serve_requests_total", 25.0, now=now,
                             sel=SEL) == pytest.approx(5.0)


def test_replica_churn_marks_stale_and_prunes():
    """Scale-in: the vanished replica's distribution must drop out of
    cross-replica window quantiles IMMEDIATELY (stale), and its rings
    prune once aged out — without breaking the surviving replica's
    windows."""
    h = FleetHistory(raw_step_s=1, raw_retention_s=20)
    now = time.time()
    slow = LatencyFeeder(h, labels={**SEL, "replica": "p-dead"})
    fast = LatencyFeeder(h, labels={**SEL, "replica": "p-live"})
    slow.feed(now - 15, now, 1, bad_frac=1.0)
    fast.feed(now - 15, now, 1, bad_frac=0.0)
    q = h.window_quantile("serve_ttft_seconds", 0.9, 12.0, now=now,
                          sel=SEL)
    assert q > 0.1  # the dead-to-be replica's tail dominates p90
    assert h.mark_stale(replica="p-dead") == 1
    q = h.window_quantile("serve_ttft_seconds", 0.9, 12.0, now=now,
                          sel=SEL)
    assert q <= 0.05  # only the live replica remains
    # Not yet prunable (its newest point is fresh)...
    assert h.prune(now=now) == 0
    # ...but once past raw retention it goes; the live series stays.
    assert h.prune(now=now + 30) == 1
    assert h.stats()["series"] == 1
    # A come-back replica un-stales by appending.
    h.mark_stale(replica="p-live")
    fast.snapshot_at(now + 31)
    assert h.stats()["stale"] == 0


# ---------------------------------------------------------------------------
# Burn-rate SLO transitions through the real reconciler
# ---------------------------------------------------------------------------

def test_fast_window_onset_names_window(harness):
    client, ctx, mgr = harness
    ready_slo_server(client, mgr, {"ttftP99Ms": 100})
    fl.FLEET.update(("Server", "default", "srv"), fresh_sample())
    now = time.time()
    feeder = LatencyFeeder(obs_history.HISTORY)
    # 2 h of clean traffic, then 30 min at 50% bad: burn(5m)=50x,
    # burn(1h)=25x — both over 14.4 -> the FAST pair fires. (The slow
    # pair's 6 h window is not yet computable: 2 h of history.)
    end = feeder.feed(now - 7200, now - 1801, 60, bad_frac=0.0)
    feeder.feed(end + 60, now, 60, bad_frac=0.5)

    from runbooks_tpu.controller.metrics import REGISTRY

    before = REGISTRY.counter_value(
        "controller_slo_violations_total", server="srv",
        objective="TTFTP99BurnRateFast5m")
    srv = reconcile_srv(client, mgr)
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "True"
    assert c["reason"] == "TTFTP99BurnRateFast5m"
    assert "burn" in c["message"] and "5m" in c["message"]
    assert REGISTRY.counter_value(
        "controller_slo_violations_total", server="srv",
        objective="TTFTP99BurnRateFast5m") == before + 1
    # Telemetry carries burn + budget; the budget is visibly consumed.
    telem = ko.deep_get(srv, "status", "telemetry")
    assert telem["burnRate"] > 14.4
    assert 0 <= telem["errorBudgetRemainingPct"] < 100
    # Burn gauges per window joined the registry.
    assert obs_metrics.parse_exposition(REGISTRY.render())[
        "controller_slo_burn_rate"].value(
            server="srv", namespace="default", objective="ttftP99Ms",
            window="5m") > 14.4

    # Recovery: 10 min of clean traffic clears the 5 m window -> the
    # fast pair's short window disagrees -> shed.
    feeder.feed(now + 60, now + 600, 60, bad_frac=0.0)
    import unittest.mock as mock

    with mock.patch("runbooks_tpu.controller.server.time") as fake_time:
        fake_time.time.return_value = now + 600
        srv = reconcile_srv(client, mgr)
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "False" and c["reason"] == cond.REASON_SLO_MET


def test_slow_window_persists_after_fast_clears(harness):
    """A sustained simmer: the fast pair never fires (or clears), but
    the slow 30m/6h pair holds the condition until the 30 m window is
    clean."""
    client, ctx, mgr = harness
    ready_slo_server(client, mgr, {"ttftP99Ms": 100})
    fl.FLEET.update(("Server", "default", "srv"), fresh_sample())
    now = time.time()
    feeder = LatencyFeeder(obs_history.HISTORY)
    # 6.5 h at 10% bad: burn(30m)=burn(6h)=10x — over the slow
    # threshold (6) but under the fast one (14.4); the last 6 min are
    # clean so the 5 m window is quiet from the start.
    end = feeder.feed(now - 23400, now - 361, 60, bad_frac=0.10)
    feeder.feed(end + 60, now, 60, bad_frac=0.0)
    srv = reconcile_srv(client, mgr)
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "True"
    assert c["reason"] == "TTFTP99BurnRateSlow30m"

    # 35 more clean minutes drain the 30 m window -> shed.
    feeder.feed(now + 60, now + 2100, 60, bad_frac=0.0)
    import unittest.mock as mock

    with mock.patch("runbooks_tpu.controller.server.time") as fake_time:
        fake_time.time.return_value = now + 2100
        srv = reconcile_srv(client, mgr)
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "False" and c["reason"] == cond.REASON_SLO_MET


def test_error_rate_burn_objective(harness):
    client, ctx, mgr = harness
    ready_slo_server(client, mgr, {"errorRatePct": 1})
    fl.FLEET.update(("Server", "default", "srv"), fresh_sample())
    now = time.time()
    labels = {**SEL, "replica": "p0"}
    total = failed = 0.0
    t = now - 7200
    while t <= now + 1e-9:
        total += 100.0
        if t > now - 1800:  # last 30 min: half the requests fail
            failed += 50.0
        obs_history.HISTORY.append_scalar("serve_requests_total", labels,
                                          t, total, "counter")
        obs_history.HISTORY.append_scalar("serve_requests_failed_total",
                                          labels, t, failed, "counter")
        t += 60.0
    srv = reconcile_srv(client, mgr)
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "True"
    assert c["reason"] == "ErrorRateBurnRateFast5m"


def test_instant_fallback_while_history_cold(harness):
    """No history at all: the PR-6 instant-threshold path still alerts
    with the objective-named reason."""
    client, ctx, mgr = harness
    ready_slo_server(client, mgr, {"ttftP99Ms": 100})
    fl.FLEET.update(("Server", "default", "srv"),
                    fresh_sample(ttft_s=0.4))
    srv = reconcile_srv(client, mgr)
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    assert c["status"] == "True"
    assert c["reason"] == cond.REASON_SLO_TTFT


# ---------------------------------------------------------------------------
# Snapshot persistence
# ---------------------------------------------------------------------------

def test_restart_restores_history_without_refire(harness, tmp_path):
    client, ctx, mgr = harness
    ready_slo_server(client, mgr, {"ttftP99Ms": 100})
    fl.FLEET.update(("Server", "default", "srv"), fresh_sample())
    now = time.time()
    feeder = LatencyFeeder(obs_history.HISTORY)
    end = feeder.feed(now - 7200, now - 1801, 60, bad_frac=0.0)
    feeder.feed(end + 60, now, 60, bad_frac=0.5)
    srv = reconcile_srv(client, mgr)
    assert ko.is_condition_true(srv, cond.SLO_VIOLATED)

    from runbooks_tpu.controller.metrics import REGISTRY

    onsets = REGISTRY.counter_value(
        "controller_slo_violations_total", server="srv",
        objective="TTFTP99BurnRateFast5m")
    path = str(tmp_path / "snap" / "fleet_history.json")
    assert obs_history.HISTORY.save(path)
    assert not os.path.exists(path + ".tmp")  # atomic: no temp debris

    # Controller restart: every in-process plane resets; the CR (with
    # its SLOViolated condition) survives in the cluster.
    obs_history.HISTORY.reset()
    fl.FLEET.reset()
    assert obs_history.HISTORY.load(path) == "restored"
    srv = reconcile_srv(client, mgr)   # first reconcile, pre-scrape
    c = ko.get_condition(srv, cond.SLO_VIOLATED)
    # Still violated with the same window-named reason — NOT NoTelemetry
    # (the restored rings are the evidence) and NOT a fresh onset.
    assert c["status"] == "True"
    assert c["reason"] == "TTFTP99BurnRateFast5m"
    assert REGISTRY.counter_value(
        "controller_slo_violations_total", server="srv",
        objective="TTFTP99BurnRateFast5m") == onsets


def test_corrupt_snapshot_cold_starts_loudly(tmp_path, capsys):
    h = FleetHistory()
    h.append_scalar("g", {"replica": "p0"}, time.time(), 1.0)
    path = str(tmp_path / "fleet_history.json")
    # Corrupt file: must log LOUDLY, reset, and never raise.
    with open(path, "w") as f:
        f.write('{"version": 1, "series": [{"name"')  # truncated write
    assert h.load(path) == "corrupt"
    assert h.stats()["series"] == 0
    assert "SNAPSHOT CORRUPT" in capsys.readouterr().out
    # Wrong version: same contract.
    with open(path, "w") as f:
        json.dump({"version": 99, "series": []}, f)
    assert h.load(path) == "corrupt"
    # Missing file: plain cold start, no log.
    assert h.load(str(tmp_path / "nope.json")) == "cold"
    # Unwritable destination: save returns False, never raises.
    assert h.save("/proc/definitely/not/writable.json") is False


def test_snapshot_roundtrip_preserves_windows(tmp_path):
    h = FleetHistory()
    now = time.time()
    feeder = LatencyFeeder(h)
    feeder.feed(now - 3600, now, 60, bad_frac=0.5)
    q_before = h.window_quantile("serve_ttft_seconds", 0.5, 300.0,
                                 now=now, sel={"replica": "p0"})
    h.mark_stale(replica="p0")
    path = str(tmp_path / "snap.json")
    assert h.save(path)
    h2 = FleetHistory()
    assert h2.load(path) == "restored"
    # Stale markers and histogram bounds survive; windows agree. (The
    # stale series is queried directly by replica — sel-matching stale
    # exclusion is covered above.)
    assert h2.stats()["stale"] == 1
    s2 = next(iter(h2._series.values()))
    assert s2.bounds == tuple(BOUNDS)
    assert h2.window_quantile("serve_ttft_seconds", 0.5, 300.0, now=now,
                              sel={"replica": "p0"}) is None  # stale
    s2.stale_since = None
    assert h2.window_quantile("serve_ttft_seconds", 0.5, 300.0, now=now,
                              sel={"replica": "p0"}) == q_before


# ---------------------------------------------------------------------------
# Scraper integration: ingest, self-observability, run-loop snapshots
# ---------------------------------------------------------------------------

def scrape_harness(ctx, history=None):
    registry, state = Registry(), fl.FleetState()
    scraper = fl.FleetScraper(ctx, state=state, registry=registry,
                              history=history, timeout_s=1.0)
    return scraper, registry


def test_scraper_populates_history_and_stats(harness):
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    reg = replica_registry()
    httpd = serve_metrics(0, reg)
    make_pod(client, "srv-a", {"server": "srv"}, httpd.server_address[1])
    h = FleetHistory(raw_step_s=0.1)
    scraper, registry = scrape_harness(ctx, history=h)
    try:
        scraper.scrape_once()
        reg.set_counter("serve_tokens_generated_total", 900)
        reg.observe("serve_ttft_seconds", 0.03)
        time.sleep(0.1)
        scraper.scrape_once()
        t_q = time.time()   # queries anchor here: shutdown below is slow
    finally:
        httpd.shutdown()
        httpd.server_close()
    # Mirrored families have rings with both ticks; histograms carry
    # their bucket snapshots.
    sel = {"name": "srv", "replica": "srv-a"}
    inc = h.window_increase("serve_tokens_generated_total", 0.15,
                            now=t_q, sel=sel)
    assert inc == pytest.approx(400.0)
    assert h.window_quantile("serve_ttft_seconds", 0.5, 0.15, now=t_q,
                             sel=sel) is not None
    # fleet_scrape_up + the per-pod duration histogram + stats gauges.
    assert h.window_increase("fleet_scrape_up", 0.15, now=t_q,
                             sel=sel) is not None
    fams = obs_metrics.parse_exposition(registry.render())
    assert fams["fleet_scrape_duration_seconds"].merged_histogram(
        ).count == 2
    assert fams["fleet_history_series"].value() > 0
    assert fams["fleet_history_points"].value() > 0


def test_scrape_error_counter_reasons(harness):
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    # A pod pointing at a closed port -> "unreachable".
    make_pod(client, "srv-dead", {"server": "srv"}, 1)
    scraper, registry = scrape_harness(ctx)
    scraper.scrape_once()
    fams = obs_metrics.parse_exposition(registry.render())
    assert fams["fleet_scrape_errors_total"].value(
        kind="Server", namespace="default", name="srv",
        replica="srv-dead", reason="unreachable") == 1.0
    # A Running pod with no IP -> "no-url".
    client.create({
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": "srv-noip", "namespace": "default",
                     "labels": {"server": "srv", "role": "run"}},
        "spec": {"containers": [{"name": "c"}]},
        "status": {"phase": "Running"},
    })
    scraper.scrape_once()
    fams = obs_metrics.parse_exposition(registry.render())
    assert fams["fleet_scrape_errors_total"].value(
        replica="srv-noip", kind="Server", namespace="default",
        name="srv", reason="no-url") == 1.0


def test_scraper_prune_marks_history_stale(harness):
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    reg = replica_registry()
    httpd = serve_metrics(0, reg)
    make_pod(client, "srv-a", {"server": "srv"}, httpd.server_address[1])
    h = FleetHistory(raw_step_s=0.01)
    scraper, registry = scrape_harness(ctx, history=h)
    try:
        scraper.scrape_once()
    finally:
        httpd.shutdown()
        httpd.server_close()
    assert h.stats()["stale"] == 0
    client.delete("v1", "Pod", "default", "srv-a")
    scraper.scrape_once()
    st = h.stats()
    assert st["series"] > 0 and st["stale"] == st["series"]


def test_run_loop_restores_and_saves_snapshot(harness, tmp_path):
    """The scrape loop's persistence half: restore before the first
    sweep, save on the way out — a second scraper (the restarted
    controller / new leader) starts warm."""
    client, ctx, _ = harness
    path = str(tmp_path / "hist.json")
    h = FleetHistory()
    h.append_scalar("serve_active_slots", {**SEL, "replica": "p0"},
                    time.time(), 3.0)
    h.save(path)

    h2 = FleetHistory()
    scraper = fl.FleetScraper(ctx, state=fl.FleetState(),
                              registry=Registry(), history=h2,
                              snapshot_path=path, snapshot_every_s=0.0)
    stop = threading.Event()
    thread = threading.Thread(target=scraper.run, args=(stop, 0.02))
    thread.start()
    time.sleep(0.08)
    stop.set()
    thread.join(timeout=5.0)
    assert not thread.is_alive()
    assert h2.stats()["series"] >= 1  # restored the seeded series
    # The exit save wrote back (mtime/content fresh and loadable).
    h3 = FleetHistory()
    assert h3.load(path) == "restored"


# ---------------------------------------------------------------------------
# GET /metrics/history + rbt dash
# ---------------------------------------------------------------------------

def fetch_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        assert resp.headers["Content-Type"] == "application/json"
        return json.loads(resp.read().decode())


def test_history_endpoint_bounded_json_every_family(harness):
    """After a real scrape, /metrics/history serves parseable, bounded
    JSON for EVERY mirrored family (and 400s malformed queries)."""
    client, ctx, _ = harness
    client.create(Server.new("srv", spec={"image": "x"}).obj)
    reg = replica_registry()
    reg.set_gauge("serve_kv_occupancy_ratio", 0.25)
    replica_httpd = serve_metrics(0, reg)
    make_pod(client, "srv-a", {"server": "srv"},
             replica_httpd.server_address[1])
    h = FleetHistory(raw_step_s=0.01)
    scraper, registry = scrape_harness(ctx, history=h)
    try:
        scraper.scrape_once()
        time.sleep(0.02)
        scraper.scrape_once()
    finally:
        replica_httpd.shutdown()
        replica_httpd.server_close()

    httpd = serve_metrics(0, registry, history=h)
    base = f"http://127.0.0.1:{httpd.server_address[1]}/metrics/history"
    try:
        idx = fetch_json(base)
        names = {e["name"] for e in idx["series"]}
        # Every mirrored serve_* family from the replica exposition got
        # a ring, plus the scraper's own lines.
        assert {"serve_ttft_seconds", "serve_requests_total",
                "serve_active_slots", "serve_kv_occupancy_ratio",
                "fleet_scrape_up", "fleet_tokens_per_sec"} <= names
        assert idx["config"]["raw_step_s"] == 0.01
        for name in sorted(names):
            body = fetch_json(f"{base}?series={name}&since=10&step=0.01"
                              f"&q=0.9&name=srv")
            entry = body["series"][0]
            assert entry["name"] == name
            assert len(entry["points"]) <= obs_history.MAX_QUERY_POINTS
            assert any(v is not None for _, v in entry["points"]), name
        # Bad query -> 400 with a JSON error, not a crash.
        with pytest.raises(urllib.error.HTTPError) as err:
            fetch_json(f"{base}?series=serve_ttft_seconds&q=2.0")
        assert err.value.code == 400
        # Endpoint absent without a history (plain metrics servers).
        plain = serve_metrics(0, registry)
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                fetch_json(f"http://127.0.0.1:"
                           f"{plain.server_address[1]}/metrics/history")
            assert err.value.code == 404
        finally:
            plain.shutdown()
            plain.server_close()
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_rbt_dash_end_to_end(harness, capsys):
    """`rbt dash --once` against a real scrape loop + two fake replica
    expositions: sparklines non-empty after >= 2 scrape ticks."""
    import urllib.error

    from runbooks_tpu.cli.main import main as cli_main

    client, ctx, mgr = harness
    client.create(Model.new("m", spec={"image": "loader"}).obj)
    client.create(Server.new("srv", spec={
        "image": "img", "model": {"name": "m"},
        "slo": {"ttftP99Ms": 100}}).obj)
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "m-modeller")
    mgr.reconcile_until_stable()

    regs = [replica_registry(tokens=500), replica_registry(tokens=800)]
    httpds = [serve_metrics(0, r) for r in regs]
    for i, httpd in enumerate(httpds):
        make_pod(client, f"srv-{i}", {"server": "srv"},
                 httpd.server_address[1])
    h = FleetHistory(raw_step_s=0.02)
    scraper, registry = scrape_harness(ctx, history=h)
    controller = serve_metrics(0, registry, history=h)
    url = f"http://127.0.0.1:{controller.server_address[1]}"
    try:
        # >= 2 scrape ticks with the real manager reconciling between
        # them (the reconciler folds telemetry + burn gauges).
        scraper.scrape_once()
        mgr.reconcile_until_stable()
        for r in regs:
            r.set_counter("serve_tokens_generated_total", 2000)
        time.sleep(0.05)
        scraper.scrape_once()
        mgr.reconcile_until_stable()

        rc = cli_main(["dash", "servers/srv", "--url", url, "--once",
                       "--step", "0.02", "--window", "30"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "servers/srv dashboard" in out
        for label in ("ttft p99", "queue-wait p90", "tokens/sec",
                      "error rate", "replicas up", "burn rate 5m"):
            assert label in out
        # Sparklines rendered actual data cells.
        assert any(block in out for block in "▁▂▃▄▅▆▇█")
        # The replica-count panel saw both replicas.
        line = next(l for l in out.splitlines()
                    if l.startswith("replicas up"))
        assert "2" in line
        # Fleet-wide scope (no servers/<n>) renders too.
        rc = cli_main(["dash", "--url", url, "--once", "--step", "0.02",
                       "--window", "30"])
        assert rc == 0
        assert "fleet dashboard" in capsys.readouterr().out
    finally:
        for httpd in httpds:
            httpd.shutdown()
            httpd.server_close()
        controller.shutdown()
        controller.server_close()


def test_rbt_dash_requires_url(monkeypatch):
    from runbooks_tpu.cli.main import main as cli_main

    monkeypatch.delenv("RBT_CONTROLLER_URL", raising=False)
    with pytest.raises(SystemExit) as err:
        cli_main(["dash", "--once"])
    assert "metrics/history" in str(err.value)


def test_sparkline_shapes():
    from runbooks_tpu.cli.main import _sparkline

    assert _sparkline([]) == ""
    assert _sparkline([None, None]) == ""
    assert _sparkline([1.0, 1.0]) == "▄▄"          # flat -> mid block
    line = _sparkline([0.0, None, 10.0])
    assert line[0] == "▁" and line[1] == "·" and line[2] == "█"
    assert len(_sparkline(list(range(100)), width=48)) == 48


# ---------------------------------------------------------------------------
# `rbt get` budget cell + autoscaler windowed p90
# ---------------------------------------------------------------------------

def test_rbt_get_budget_cell():
    from runbooks_tpu.cli.main import telemetry_summary

    srv = Server.new("srv", spec={"image": "x",
                                  "slo": {"ttftP99Ms": 100}}).obj
    srv["status"] = {"telemetry": {"activeSlots": 1, "burnRate": 2.5,
                                   "errorBudgetRemainingPct": 63.2}}
    cell = telemetry_summary(srv)
    assert "budget=63.2%" in cell and "burn=2.5x" in cell
    # History not warm: the field is absent -> "-" fallback.
    srv["status"] = {"telemetry": {"activeSlots": 1}}
    assert "budget=-" in telemetry_summary(srv)
    # No slo -> no budget cell at all.
    plain = Server.new("p", spec={"image": "x"}).obj
    plain["status"] = {"telemetry": {"activeSlots": 1}}
    assert "budget" not in telemetry_summary(plain)


def test_autoscaler_reads_windowed_p90_and_excludes_stale(harness):
    """The scale-out signal comes from the HISTORY window quantile once
    warm — a low instant p90 cannot mask a sustained-high window — and
    stale replicas' rings are excluded from that window."""
    from runbooks_tpu.controller import autoscale as autoscale_mod

    client, ctx, mgr = harness
    autoscale_mod.AUTOSCALE.reset()
    client.create(Model.new("m", spec={"image": "loader"}).obj)
    client.create(Server.new("srv", spec={
        "image": "img", "model": {"name": "m"},
        "autoscale": {"minReplicas": 1, "maxReplicas": 3,
                      "queueWaitP90Ms": 50, "scaleOutSustainS": 0,
                      "cooldownS": 0}}).obj)
    mgr.reconcile_until_stable()
    client.mark_job_complete("default", "m-modeller")
    mgr.reconcile_until_stable()

    # Instant telemetry is HEALTHY (queue-wait ~1 ms)...
    fl.FLEET.update(("Server", "default", "srv"), fresh_sample())
    # ...but the last 60 s of history hold a sustained 250 ms p90.
    now = time.time()
    feeder = LatencyFeeder(obs_history.HISTORY,
                           labels={**SEL, "replica": "srv-pod"},
                           name="serve_queue_wait_seconds")
    feeder.feed(now - 60, now, 5, bad_frac=1.0)
    make_pod(client, "srv-pod", {"server": "srv"}, 9999)
    srv = reconcile_srv(client, mgr)
    status = ko.deep_get(srv, "status", "autoscale")
    assert status["desiredReplicas"] == 2  # scaled out on the window
    assert status["lastAction"] == "out"

    # Stale exclusion: the only ring goes stale -> window p90 is gone
    # -> the healthy instant p90 rules and nothing scales further.
    autoscale_mod.AUTOSCALE.reset()
    obs_history.HISTORY.mark_stale(replica="srv-pod")
    srv = reconcile_srv(client, mgr)
    status = ko.deep_get(srv, "status", "autoscale")
    assert status["desiredReplicas"] == 2  # re-clamped base, no new out
    assert "lastAction" not in status


def test_burn_rate_math_units():
    """Unit sanity directly on the evaluator: a fleet burning exactly
    its budget reads 1.0x."""
    h = FleetHistory()
    now = time.time()
    feeder = LatencyFeeder(h)
    # Exactly 1% of events above a p99 target -> burn 1.0 on every
    # window; budget remaining stays 0..100.
    feeder.feed(now - 7200, now, 60, bad_frac=0.01)
    verdicts = burnrate.evaluate({"ttftP99Ms": 100}, h, SEL, now=now)
    assert len(verdicts) == 1
    v = verdicts[0]
    assert v.computable and v.fired is None
    assert v.burn["5m"] == pytest.approx(1.0, rel=1e-6)
    assert v.burn["1h"] == pytest.approx(1.0, rel=1e-6)
    assert v.budget_remaining_pct == pytest.approx(0.0, abs=1e-6)
