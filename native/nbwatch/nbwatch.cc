// nbwatch — file-change watcher for the notebook sync loop.
//
// Native C++ equivalent of the reference's Go fsnotify tool (reference:
// containertools/cmd/nbwatch/main.go): watches a root directory (default
// /content) non-recursively plus its first-level subdirectories, skipping
// the contract mounts (data/, model/, artifacts/) and dotfiles, and emits
// one JSON object per event on stdout:
//
//   {"index":0,"path":"/content/train.py","op":"WRITE"}
//
// The CLI-side sync loop (runbooks_tpu/utils/sync.py) execs this inside the
// notebook pod and mirrors changed files back to the workstation.
//
// Build: make -C native/nbwatch   (static-ish, no deps beyond libc/libstdc++)

#include <sys/inotify.h>
#include <sys/stat.h>

#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <map>
#include <string>
#include <unistd.h>

namespace {

const char *kSkipDirs[] = {"data", "model", "artifacts"};

bool ShouldSkipDir(const std::string &name) {
  if (!name.empty() && name[0] == '.') return true;
  for (const char *skip : kSkipDirs) {
    if (name == skip) return true;
  }
  return false;
}

bool ShouldSkipFile(const std::string &name) {
  return name.empty() || name[0] == '.' || name.back() == '~';
}

const char *OpName(uint32_t mask) {
  if (mask & IN_CREATE) return "CREATE";
  if (mask & IN_CLOSE_WRITE) return "WRITE";
  if (mask & IN_MODIFY) return "WRITE";
  if (mask & (IN_MOVED_FROM | IN_MOVE_SELF)) return "RENAME";
  if (mask & IN_MOVED_TO) return "CREATE";
  if (mask & IN_DELETE) return "REMOVE";
  return "OTHER";
}

void JsonEscape(const std::string &in, std::string *out) {
  for (char c : in) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\n': *out += "\\n"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          *out += buf;
        } else {
          *out += c;
        }
    }
  }
}

}  // namespace

int main(int argc, char **argv) {
  std::string root = argc > 1 ? argv[1] : "/content";
  int fd = inotify_init1(IN_CLOEXEC);
  if (fd < 0) {
    perror("inotify_init1");
    return 1;
  }

  const uint32_t mask = IN_CLOSE_WRITE | IN_CREATE | IN_DELETE |
                        IN_MOVED_FROM | IN_MOVED_TO;
  std::map<int, std::string> watch_dirs;

  auto add_watch = [&](const std::string &dir) {
    int wd = inotify_add_watch(fd, dir.c_str(), mask);
    if (wd >= 0) {
      watch_dirs[wd] = dir;
      fprintf(stderr, "nbwatch: watching %s\n", dir.c_str());
    }
  };

  // Root + first-level subdirectories (non-recursive, like the reference).
  add_watch(root);
  if (DIR *d = opendir(root.c_str())) {
    while (dirent *ent = readdir(d)) {
      std::string name = ent->d_name;
      if (name == "." || name == ".." || ShouldSkipDir(name)) continue;
      std::string full = root + "/" + name;
      struct stat st;
      if (stat(full.c_str(), &st) == 0 && S_ISDIR(st.st_mode)) {
        add_watch(full);
      }
    }
    closedir(d);
  }

  // Announce readiness on stdout: the sync loop uses this to tell a healthy
  // watcher on an idle pod apart from a binary that failed to exec at all.
  {
    std::string escaped;
    JsonEscape(root, &escaped);
    printf("{\"index\":-1,\"path\":\"%s\",\"op\":\"READY\"}\n",
           escaped.c_str());
    fflush(stdout);
  }

  long index = 0;
  char buf[4096 * 4];
  for (;;) {
    ssize_t len = read(fd, buf, sizeof buf);
    if (len <= 0) {
      if (len < 0 && errno == EINTR) continue;
      break;
    }
    for (char *p = buf; p < buf + len;) {
      auto *ev = reinterpret_cast<inotify_event *>(p);
      p += sizeof(inotify_event) + ev->len;
      if (ev->len == 0) continue;
      std::string name = ev->name;
      auto it = watch_dirs.find(ev->wd);
      if (it == watch_dirs.end()) continue;
      if (ev->mask & IN_ISDIR) {
        // New first-level directory: start watching it (unless skipped).
        if ((ev->mask & IN_CREATE) && it->second == root &&
            !ShouldSkipDir(name)) {
          add_watch(it->second + "/" + name);
        }
        continue;
      }
      if (ShouldSkipFile(name)) continue;
      std::string path = it->second + "/" + name;
      std::string escaped;
      JsonEscape(path, &escaped);
      printf("{\"index\":%ld,\"path\":\"%s\",\"op\":\"%s\"}\n", index++,
             escaped.c_str(), OpName(ev->mask));
      fflush(stdout);
    }
  }
  return 0;
}
