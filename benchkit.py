"""Outer bench harness: run the real benchmark in a subprocess, robust to a
dead or wedged TPU relay.

Round-1 post-mortem (VERDICT.md "What's weak" 1-2): bench.py crashed (rc=1)
when the axon relay was down because JAX backend init raised in-process, and
the multichip dryrun hung (rc=124) because backend init blocked on a dead
relay socket. The durable fix is to never touch the default JAX backend in
the orchestrating process at all:

- the orchestrator is stdlib-only (no jax import);
- it preflights the relay TCP socket before attempting TPU;
- the actual bench runs in a subprocess (``python bench.py --inner``) with a
  timeout, so a wedged backend init cannot take down the artifact;
- on TPU failure it retries once (the relay is single-client, so a transient
  collision is plausible), then falls back to forced-CPU;
- it ALWAYS prints exactly one JSON line, with the platform and any errors
  recorded, and exits 0.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

RELAY_PORT = 8082


def relay_reachable(timeout: float = 2.0) -> bool:
    """Is it safe to touch the default JAX backend? True when no relay
    plugin is configured (nothing to preflight — plain TPU VMs or CPU boxes
    init fine), else a cheap TCP-connect to every pool IP. Single source of
    truth for this check — __graft_entry__ imports it."""
    ips = [s.strip() for s in
           (os.environ.get("PALLAS_AXON_POOL_IPS") or "").split(",")
           if s.strip()]
    for ip in ips:
        try:
            socket.create_connection((ip, RELAY_PORT), timeout).close()
        except OSError:
            return False
    return True


def apply_cpu_env(env=None, n_devices: int = 1):
    """Pin an environment mapping to CPU with n virtual devices and disable
    the relay dial. The one place the pinning recipe lives (used by the
    bench orchestrator, tests/conftest.py, and __graft_entry__'s dryrun);
    mutates and returns ``env`` (default: os.environ).

    An existing device-count flag is REPLACED, not kept: a second call
    asking for more devices (e.g. entry() pinned 1, dryrun needs 8) must
    win — though it only takes effect if the CPU backend has not been
    initialized yet."""
    import re
    env = os.environ if env is None else env
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # sitecustomize skips the axon hook
    flags = env.get("XLA_FLAGS", "")
    count_flag = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       count_flag, flags)
    else:
        flags = (flags + " " + count_flag).strip()
    env["XLA_FLAGS"] = flags
    return env


def cpu_env(n_devices: int = 1) -> dict:
    """A copy of os.environ pinned to CPU (for subprocesses)."""
    return apply_cpu_env(dict(os.environ), n_devices)


def _run_inner(script: str, env: dict, timeout: float):
    """Run ``script --inner``; return (parsed-json-or-None, error-or-None,
    elapsed-seconds)."""
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, script, "--inner"], env=env, timeout=timeout,
            capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        tail = (e.stderr or "")
        if isinstance(tail, bytes):
            tail = tail.decode("utf-8", "replace")
        return None, f"timeout after {timeout:.0f}s: {tail[-1500:]}", \
            time.time() - t0
    elapsed = time.time() - t0
    sys.stderr.write(proc.stderr[-4000:])
    if proc.returncode != 0:
        return None, f"rc={proc.returncode}: {proc.stderr[-1500:]}", elapsed
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None, elapsed
            except json.JSONDecodeError:
                continue
    return None, f"no JSON in stdout: {proc.stdout[-1500:]}", elapsed


def run_outer(script: str, fallback_metric: str, unit: str) -> None:
    """Orchestrate TPU-then-CPU attempts of ``script``; always print JSON."""
    print(json.dumps(measure_outer(script, fallback_metric, unit)))


def measure_outer(script: str, fallback_metric: str, unit: str) -> dict:
    """Like run_outer but returns the result dict instead of printing, so a
    caller can compose several benchmarks into one driver-visible JSON line
    (bench.py folds bench_serve's TTFT/decode numbers in this way)."""
    errors: list[str] = []
    result = None
    tpu_timeout = float(os.environ.get("RBT_BENCH_TPU_TIMEOUT", 1200))
    cpu_timeout = float(os.environ.get("RBT_BENCH_CPU_TIMEOUT", 900))

    if os.environ.get("RBT_BENCH_FORCE_CPU") == "1":
        errors.append("RBT_BENCH_FORCE_CPU=1: skipping TPU attempt")
    elif not relay_reachable():
        errors.append("tpu relay unreachable: skipping TPU attempt")
    else:
        result, err, elapsed = _run_inner(script, dict(os.environ),
                                          tpu_timeout)
        if result is None:
            errors.append(f"tpu attempt 1: {err}")
            # Retry only quick failures (a slow failure was likely a hang or
            # a compile that won't improve; a quick one may be a transient
            # relay collision — the relay is single-client).
            if elapsed < 180 and relay_reachable():
                time.sleep(10)
                result, err, _ = _run_inner(script, dict(os.environ),
                                            tpu_timeout)
                if result is None:
                    errors.append(f"tpu attempt 2: {err}")

    if result is None:
        # A multi-chip bench axis (RBT_BENCH_MESH_TENSOR) still needs that
        # many devices on the CPU fallback — virtualize them.
        n_cpu = max(1, int(os.environ.get("RBT_BENCH_MESH_TENSOR", "1")))
        result, err, _ = _run_inner(script, cpu_env(n_cpu), cpu_timeout)
        if result is None:
            errors.append(f"cpu attempt: {err}")

    if result is None:
        result = {"metric": fallback_metric, "value": 0.0, "unit": unit,
                  "vs_baseline": 0.0, "platform": "none"}
    if errors:
        result["bench_errors"] = errors
    return result
